"""Decision validation + feasibility (parity: reference scheduler.py:453-465)."""

from k8s_llm_scheduler_tpu.core.validation import (
    feasible_nodes,
    resources_fit,
    selector_matches,
    tolerates_taints,
    validate_decision,
)
from k8s_llm_scheduler_tpu.types import SchedulingDecision

from conftest import make_node, make_pod


def decision(node):
    return SchedulingDecision(selected_node=node, confidence=0.9, reasoning="")


class TestValidateDecision:
    def test_known_node_accepted(self, three_nodes):
        assert validate_decision(decision("node-b"), three_nodes)

    def test_hallucinated_node_rejected(self, three_nodes):
        assert not validate_decision(decision("node-x"), three_nodes)
        assert not validate_decision(decision(""), three_nodes)


class TestFeasibility:
    def test_selector(self):
        node = make_node("n", labels={"disktype": "ssd"})
        assert selector_matches(make_pod(node_selector={"disktype": "ssd"}), node)
        assert not selector_matches(make_pod(node_selector={"disktype": "hdd"}), node)
        assert selector_matches(make_pod(), node)  # empty selector matches all

    def test_taints(self):
        tainted = make_node("n", taints=({"key": "gpu", "effect": "NoSchedule"},))
        assert not tolerates_taints(make_pod(), tainted)
        assert tolerates_taints(
            make_pod(tolerations=({"key": "gpu", "effect": "NoSchedule"},)), tainted
        )
        assert tolerates_taints(
            make_pod(tolerations=({"key": "gpu"},)), tainted
        )  # effect-less toleration matches any effect
        # PreferNoSchedule is soft — never blocks
        soft = make_node("n", taints=({"key": "x", "effect": "PreferNoSchedule"},))
        assert tolerates_taints(make_pod(), soft)

    def test_resources(self):
        node = make_node("n", cpu_cores=1.0, mem_gb=1.0, pods=109, max_pods=110)
        assert resources_fit(make_pod(cpu=0.5, mem_gb=0.5), node)
        assert not resources_fit(make_pod(cpu=2.0, mem_gb=0.5), node)
        assert not resources_fit(make_pod(cpu=0.5, mem_gb=2.0), node)
        full = make_node("n", pods=110, max_pods=110)
        assert not resources_fit(make_pod(), full)

    def test_feasible_nodes_composition(self, three_nodes):
        nodes = three_nodes + [
            make_node("down", ready=False),
            make_node("tainted", taints=({"key": "x", "effect": "NoSchedule"},)),
        ]
        names = {n.name for n in feasible_nodes(make_pod(), nodes)}
        assert names == {"node-a", "node-b", "node-c"}
