"""Decision validation + feasibility (parity: reference scheduler.py:453-465)."""

from k8s_llm_scheduler_tpu.core.validation import (
    feasible_nodes,
    resources_fit,
    selector_matches,
    tolerates_taints,
    validate_decision,
)
from k8s_llm_scheduler_tpu.types import SchedulingDecision

from conftest import make_node, make_pod


def decision(node):
    return SchedulingDecision(selected_node=node, confidence=0.9, reasoning="")


class TestValidateDecision:
    def test_known_node_accepted(self, three_nodes):
        assert validate_decision(decision("node-b"), three_nodes)

    def test_hallucinated_node_rejected(self, three_nodes):
        assert not validate_decision(decision("node-x"), three_nodes)
        assert not validate_decision(decision(""), three_nodes)


class TestFeasibility:
    def test_selector(self):
        node = make_node("n", labels={"disktype": "ssd"})
        assert selector_matches(make_pod(node_selector={"disktype": "ssd"}), node)
        assert not selector_matches(make_pod(node_selector={"disktype": "hdd"}), node)
        assert selector_matches(make_pod(), node)  # empty selector matches all

    def test_taints(self):
        tainted = make_node("n", taints=({"key": "gpu", "effect": "NoSchedule"},))
        assert not tolerates_taints(make_pod(), tainted)
        assert tolerates_taints(
            make_pod(tolerations=({"key": "gpu", "effect": "NoSchedule"},)), tainted
        )
        assert tolerates_taints(
            make_pod(tolerations=({"key": "gpu"},)), tainted
        )  # effect-less toleration matches any effect
        # PreferNoSchedule is soft — never blocks
        soft = make_node("n", taints=({"key": "x", "effect": "PreferNoSchedule"},))
        assert tolerates_taints(make_pod(), soft)

    def test_resources(self):
        node = make_node("n", cpu_cores=1.0, mem_gb=1.0, pods=109, max_pods=110)
        assert resources_fit(make_pod(cpu=0.5, mem_gb=0.5), node)
        assert not resources_fit(make_pod(cpu=2.0, mem_gb=0.5), node)
        assert not resources_fit(make_pod(cpu=0.5, mem_gb=2.0), node)
        full = make_node("n", pods=110, max_pods=110)
        assert not resources_fit(make_pod(), full)

    def test_feasible_nodes_composition(self, three_nodes):
        nodes = three_nodes + [
            make_node("down", ready=False),
            make_node("tainted", taints=({"key": "x", "effect": "NoSchedule"},)),
        ]
        names = {n.name for n in feasible_nodes(make_pod(), nodes)}
        assert names == {"node-a", "node-b", "node-c"}


class TestNodeAffinity:
    """requiredDuringScheduling node affinity — live here, always {} in the
    reference (scheduler.py:762)."""

    def _pod(self, terms):
        from conftest import make_pod
        import dataclasses

        pod = make_pod()
        return dataclasses.replace(
            pod, affinity_rules={"node_affinity_terms": terms}
        )

    def test_no_rules_matches_everything(self):
        from k8s_llm_scheduler_tpu.core.validation import node_affinity_matches
        from conftest import make_node, make_pod

        assert node_affinity_matches(make_pod(), make_node(labels={}))

    def test_in_and_notin(self):
        from k8s_llm_scheduler_tpu.core.validation import node_affinity_matches
        from conftest import make_node

        pod = self._pod([[{"key": "zone", "operator": "In", "values": ["z1", "z2"]}]])
        assert node_affinity_matches(pod, make_node(labels={"zone": "z1"}))
        assert not node_affinity_matches(pod, make_node(labels={"zone": "z9"}))
        assert not node_affinity_matches(pod, make_node(labels={}))

        pod = self._pod([[{"key": "arch", "operator": "NotIn", "values": ["arm64"]}]])
        assert not node_affinity_matches(pod, make_node(labels={"arch": "arm64"}))
        assert node_affinity_matches(pod, make_node(labels={"arch": "amd64"}))
        # K8s: NotIn also matches nodes without the label
        assert node_affinity_matches(pod, make_node(labels={}))

    def test_exists_doesnotexist_gt_lt(self):
        from k8s_llm_scheduler_tpu.core.validation import node_affinity_matches
        from conftest import make_node

        pod = self._pod([[{"key": "gpu", "operator": "Exists"}]])
        assert node_affinity_matches(pod, make_node(labels={"gpu": "a100"}))
        assert not node_affinity_matches(pod, make_node(labels={}))

        pod = self._pod([[{"key": "gpu", "operator": "DoesNotExist"}]])
        assert not node_affinity_matches(pod, make_node(labels={"gpu": "a100"}))
        assert node_affinity_matches(pod, make_node(labels={}))

        pod = self._pod([[{"key": "cores", "operator": "Gt", "values": ["8"]}]])
        assert node_affinity_matches(pod, make_node(labels={"cores": "16"}))
        assert not node_affinity_matches(pod, make_node(labels={"cores": "4"}))
        assert not node_affinity_matches(pod, make_node(labels={"cores": "lots"}))

        pod = self._pod([[{"key": "cores", "operator": "Lt", "values": ["8"]}]])
        assert node_affinity_matches(pod, make_node(labels={"cores": "4"}))
        assert not node_affinity_matches(pod, make_node(labels={"cores": "16"}))

    def test_match_fields_expression_matches_node_name(self):
        """Field-tagged expressions (from matchFields) gate on metadata.name,
        not labels — K8s's only supported matchFields key."""
        from k8s_llm_scheduler_tpu.core.validation import node_affinity_matches
        from conftest import make_node

        pod = self._pod([[{
            "key": "metadata.name", "operator": "In",
            "values": ["node-a"], "field": True,
        }]])
        assert node_affinity_matches(pod, make_node("node-a"))
        assert not node_affinity_matches(pod, make_node("node-b"))
        # a metadata.name *label* must not satisfy a field expression
        assert not node_affinity_matches(
            pod, make_node("node-b", labels={"metadata.name": "node-a"})
        )

    def test_terms_or_expressions_and(self):
        from k8s_llm_scheduler_tpu.core.validation import node_affinity_matches
        from conftest import make_node

        pod = self._pod([
            [
                {"key": "zone", "operator": "In", "values": ["z1"]},
                {"key": "gpu", "operator": "Exists"},
            ],
            [{"key": "pool", "operator": "In", "values": ["batch"]}],
        ])
        # first term: BOTH expressions must hold
        assert not node_affinity_matches(pod, make_node(labels={"zone": "z1"}))
        assert node_affinity_matches(
            pod, make_node(labels={"zone": "z1", "gpu": "a100"})
        )
        # OR: second term alone suffices
        assert node_affinity_matches(pod, make_node(labels={"pool": "batch"}))
        assert not node_affinity_matches(pod, make_node(labels={"pool": "web"}))

    def test_unknown_operator_fails_closed(self):
        from k8s_llm_scheduler_tpu.core.validation import node_affinity_matches
        from conftest import make_node

        pod = self._pod([[{"key": "zone", "operator": "Regex", "values": [".*"]}]])
        assert not node_affinity_matches(pod, make_node(labels={"zone": "z1"}))

    def test_feasible_nodes_enforces_affinity(self):
        from k8s_llm_scheduler_tpu.core.validation import feasible_nodes
        from conftest import make_node

        nodes = [
            make_node("zoned", labels={"zone": "z1"}),
            make_node("other", labels={"zone": "z2"}),
        ]
        pod = self._pod([[{"key": "zone", "operator": "In", "values": ["z1"]}]])
        assert [n.name for n in feasible_nodes(pod, nodes)] == ["zoned"]
