"""Same-process decode A/Bs: matmul impls, and speculative vs plain.

Cross-run numbers on the tunneled bench chip are weather-confounded
(dispatch RTT swings 100-250 ms over hours) and 8B-scale runs pay minutes
of host init + weight transfer EACH — so this harness builds ONE set of
weights and runs both arms back to back in one process, interleaved
A/B/A/B to cancel slow drift.

Arms:
- ``--arm matmul`` (default): dense vs ragged block-decode matmuls through
  bench.model_throughput's wave phase (the VERDICT r4 item 2/5 numbers).
- ``--arm spec``: the async speculative pipeline (spec/decoder.py) vs the
  FUSED decode baseline through bench.spec_ab, grammar-constrained greedy
  by default. ``--draft self`` is the acceptance-1.0 / overlap-1.0 upper
  bound; named configs at random init measure the overhead floor (the
  production draft is a train/distill.py checkpoint).
- ``--arm hidden``: the draft-free hidden-transfer arm vs the same fused
  baseline — no second model; random-init heads here, train/hidden.py
  checkpoints in production.

Usage:
    python tools/ab_decode.py --model llama-3.2-1b-instruct
    python tools/ab_decode.py --model llama-3.1-8b-instruct --quantize int8
    python tools/ab_decode.py --arm spec --model llama-3.2-1b-instruct \
        --draft tiny --spec-k 4

Prints one JSON line per (impl, rep) plus a final summary line with the
throughput ratios.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b-instruct")
    ap.add_argument("--quantize", default=None)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--peak-tflops", type=float, default=None)
    ap.add_argument(
        "--arm", choices=("matmul", "spec", "hidden", "fused"),
        default="matmul",
        help="matmul: dense-vs-ragged wave decode; spec: async "
             "speculative pipeline vs FUSED decode baseline; hidden: the "
             "draft-free hidden-transfer arm vs the same baseline "
             "(spec/hidden.py — no second model); fused: fused "
             "while_loop runtime vs sparse chunked decode (engine/fused/)"
             " — greedy token identity is test-pinned "
             "(tests/test_fused.py, tests/test_spec_async.py); the spec "
             "arms additionally report the round-overlap fraction and "
             "acceptance-weighted tok/s",
    )
    ap.add_argument(
        "--draft", default="tiny",
        help="spec arm: draft config name, or 'self' for the "
             "acceptance-1.0 / overlap-1.0 upper bound",
    )
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument(
        "--unconstrained", action="store_true",
        help="spec/hidden arms: drop the decision grammar (default "
             "measures grammar-constrained greedy — the serving shape)",
    )
    args = ap.parse_args()

    import jax

    from k8s_llm_scheduler_tpu.models.llama import init_params

    cfg = bench.build_cfg(args.model)

    if args.arm == "fused":
        if args.quantize == "int8":
            from k8s_llm_scheduler_tpu.models.quant import init_params_int8_host

            params = init_params_int8_host(0, cfg)
        else:
            params = init_params(jax.random.PRNGKey(0), cfg)
        # fused_ab interleaves its arms internally; reps widens the best-of
        summary = bench.fused_ab(
            args.model, quantize=args.quantize, reps=args.reps,
            n_prompts=min(args.slots, 8), params=params,
            peak_override=args.peak_tflops,
        )
        print(json.dumps(summary), flush=True)
        return
    if args.arm in ("spec", "hidden"):
        if args.quantize is not None:
            ap.error(
                f"--arm {args.arm} does not take --quantize (plain bf16 A/B)"
            )
        params = init_params(jax.random.PRNGKey(0), cfg)
        # spec_ab interleaves its arms internally; reps widens the best-of
        summary = bench.spec_ab(
            args.model, draft=args.draft, spec_k=args.spec_k,
            reps=args.reps, params=params,
            arm="hidden" if args.arm == "hidden" else "draft",
            constrained=not args.unconstrained,
        )
        print(json.dumps(summary), flush=True)
        return
    if args.quantize == "int8":
        from k8s_llm_scheduler_tpu.models.quant import init_params_int8_host

        params = init_params_int8_host(0, cfg)
    else:
        params = init_params(jax.random.PRNGKey(0), cfg)

    results: dict[str, list[dict]] = {"dense": [], "ragged": []}
    for rep in range(args.reps):
        for impl in ("dense", "ragged"):
            r = bench.model_throughput(
                args.model, args.quantize, args.peak_tflops,
                slots=args.slots, decode_matmul=impl, params=params,
            )
            r["extra"]["rep"] = rep
            results[impl].append(r)
            print(json.dumps(r), flush=True)

    def best(impl: str, key: str) -> float:
        return max(r["extra"][key] for r in results[impl])

    summary = {
        "metric": "decode_matmul_ab",
        "model": args.model,
        "quantize": args.quantize,
        "reps": args.reps,
        "decisions_per_s": {
            impl: [r["extra"]["decisions_per_s"] for r in results[impl]]
            for impl in results
        },
        "mfu_decode": {
            impl: [r["extra"].get("mfu_decode") for r in results[impl]]
            for impl in results
        },
        "wave_avg_ms": {
            impl: [r["extra"]["wave_avg_ms"] for r in results[impl]]
            for impl in results
        },
        "speedup_decisions_per_s": round(
            best("ragged", "decisions_per_s") / best("dense", "decisions_per_s"), 3
        ),
    }
    if results["dense"][0]["extra"].get("mfu_decode") is not None:
        summary["mfu_decode_ratio"] = round(
            best("ragged", "mfu_decode") / best("dense", "mfu_decode"), 3
        )
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
