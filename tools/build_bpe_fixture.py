"""Build the committed BPE tokenizer fixture (assets/bpe4k).

A real, loadable HuggingFace fast tokenizer — byte-level BPE, 4096 total
vocab, Llama-3-style special tokens and chat template — trained on the
framework's OWN prompt surface (cluster-state blocks, pod suffixes, JSON
decisions) so the merges compress the scheduling prompt the way a real
checkpoint's 128k BPE would (~3-4 chars/token vs the ByteTokenizer's 1).

Purpose (VERDICT round 1, items 3/5): exercises the real-checkpoint path
hermetically — HFTokenizerAdapter (pad sentinel, chat-template split),
build_decision_dfa over multi-token BPE node names, and BPE-length prompts
in bench.py — with zero network access. Deterministic: re-running this
script reproduces the fixture byte-for-byte (fixed corpus, no RNG).

Usage: python tools/build_bpe_fixture.py   (writes k8s_llm_scheduler_tpu/assets/bpe4k/)
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

# BPE merges exhaust when every pre-tokenized word is a single token; on
# this (deliberately narrow) prompt corpus that happens well under 4k, so
# the final vocab is trained-to-exhaustion then PADDED with reserved
# tokens to the next multiple of 128 (MXU-friendly embedding rows, and
# cfg.vocab_size must equal len(tokenizer) for the engine).
VOCAB_CAP = 4096
SPECIALS = [
    "<|pad|>",
    "<|begin_of_text|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|eot_id|>",
    "<|reserved_special_0|>",
    "<|reserved_special_1|>",
    "<|reserved_special_2|>",
]
CHAT_TEMPLATE = (
    "{{ '<|begin_of_text|>' }}"
    "{% for message in messages %}"
    "{{ '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n' "
    "+ message['content'] + '<|eot_id|>' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n' }}"
    "{% endif %}"
)


def corpus() -> list[str]:
    """Deterministic training text covering the framework's prompt surface."""
    from k8s_llm_scheduler_tpu.cluster.interface import raw_pod_to_spec
    from k8s_llm_scheduler_tpu.core.prompt import PromptEngine
    from k8s_llm_scheduler_tpu.testing import pod_burst, synthetic_cluster

    pe = PromptEngine()
    texts = [pe.system_prompt]
    for n_nodes in (3, 16, 64, 200, 256):
        cluster = synthetic_cluster(n_nodes)
        try:
            nodes = cluster.get_node_metrics()
            pods = [raw_pod_to_spec(p) for p in pod_burst(32, distinct_shapes=32)]
            cluster_part, pod_part = pe.split_prompt(pods[0], nodes)
            texts.append(cluster_part)
            for pod in pods:
                texts.append(pe.split_prompt(pod, nodes)[1])
            for node in nodes:
                texts.append(
                    json.dumps(
                        {
                            "selected_node": node.name,
                            "confidence": 0.87,
                            "reasoning": f"{node.name} has the lowest combined "
                            "cpu and memory utilization with capacity headroom",
                        }
                    )
                )
        finally:
            cluster.close()
    # decimal variety so usage figures tokenize reasonably
    texts.extend(f"{i / 10:.1f}% {i}.00 GB {i}.{i:02d} cores 0.{i:03d}" for i in range(200))
    return texts


def main() -> None:
    out_dir = Path(__file__).resolve().parent.parent / "k8s_llm_scheduler_tpu" / "assets" / "bpe4k"
    out_dir.mkdir(parents=True, exist_ok=True)

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=VOCAB_CAP,
        special_tokens=SPECIALS,
        show_progress=False,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(corpus(), trainer=trainer)
    trained = tok.get_vocab_size()
    total = -(-trained // 128) * 128
    tok.add_special_tokens([f"<|vocab_pad_{i}|>" for i in range(total - trained)])
    got = tok.get_vocab_size()
    assert got == total and got <= VOCAB_CAP, (trained, got)
    tok.save(str(out_dir / "tokenizer.json"))

    config = {
        "tokenizer_class": "PreTrainedTokenizerFast",
        "model_max_length": 131072,
        "bos_token": "<|begin_of_text|>",
        "eos_token": "<|eot_id|>",
        "pad_token": "<|pad|>",
        "chat_template": CHAT_TEMPLATE,
    }
    (out_dir / "tokenizer_config.json").write_text(json.dumps(config, indent=2) + "\n")

    # smoke: load through the adapter and round-trip a prompt
    from k8s_llm_scheduler_tpu.engine.tokenizer import HFTokenizerAdapter

    adapter = HFTokenizerAdapter(str(out_dir))
    assert adapter.vocab_size == got
    assert adapter.pad_id == 0 and adapter.eos_id == SPECIALS.index("<|eot_id|>")
    pfx, sfx = adapter.chat_prompt_parts("sys", "CLUSTER STATE:\n\nNode: node-1\n", "POD TO SCHEDULE: x")
    assert pfx and sfx, "chat split degraded"
    sample = "Node: node-17\n  CPU: 37.0% used, 16.00 cores allocatable\n"
    ids = adapter.encode(sample)
    assert adapter.decode(ids) == sample
    print(f"wrote {out_dir} (vocab {got}, sample compression "
          f"{len(sample) / len(ids):.2f} chars/token)")


if __name__ == "__main__":
    main()
