"""2-process CPU dryrun of the multi-host (DCN) scaffolding.

Parent mode (no args): picks a free port, spawns two child processes of
itself (JAX_PLATFORMS=cpu, 4 virtual devices each), and checks both
succeed. Child mode (--process-id): initializes distributed JAX (8 global
devices across 2 processes) and runs:

1. a dp-over-DCN TRAIN STEP: hybrid mesh {dp:2 (across hosts)} x
   {tp:2 (within host)}, per-process local batch shard assembled into the
   global array — the gradient all-reduce crosses the process boundary
   (the DCN path on real hardware, SURVEY §2.3 DP row);
2. a SHARDED SERVING DECISION per host: each process serves its own
   replica (weights replicated across hosts, tp=2 within the host — the
   multi-host serving layout in SCALING.md), with the flash kernels on
   under shard_map;
3. process-0-only watch/bind: only the coordinator binds the decision to
   the (fake) cluster — worker hosts never touch the control plane.

Run: python tools/dryrun_multihost.py
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def child(process_id: int, port: int) -> None:
    import logging

    logging.basicConfig(level=logging.INFO)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, str(REPO))
    from k8s_llm_scheduler_tpu.parallel.distributed import (
        init_distributed,
        is_coordinator,
        multihost_mesh,
    )

    multi = init_distributed(f"localhost:{port}", 2, process_id)
    assert multi, "expected multi-process"
    assert jax.process_count() == 2
    assert jax.device_count() == 8, jax.device_count()

    # ---- 1. dp-over-DCN train step -------------------------------------
    from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
    from k8s_llm_scheduler_tpu.train.train_step import make_train_step

    cfg = LlamaConfig(
        name="dryrun-mh", vocab_size=256, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, rope_theta=10000.0,
        dtype=jnp.float32, tie_embeddings=True,
    )
    mesh = multihost_mesh({"dp": 2}, {"tp": 2})
    assert mesh.shape == {"dp": 2, "tp": 2}
    # the dp axis genuinely spans processes
    procs_along_dp = {
        d.process_index for d in mesh.devices[:, 0]
    }
    assert len(procs_along_dp) == 2, "dp axis does not cross processes"

    init_fn, step_fn = make_train_step(cfg, mesh)
    B, S = 4, 64
    state = init_fn(jax.random.PRNGKey(0))
    rng = np.random.default_rng(42)  # same seed -> same global batch
    global_tokens = rng.integers(0, 256, size=(B, S), dtype=np.int32)
    global_lens = np.full((B,), S, dtype=np.int32)
    # the REAL data path: place_batch slices this process's dp rows and
    # assembles the global arrays (train/train_step.py)
    tokens, seq_lens = step_fn.place_batch(global_tokens, global_lens)
    state, loss = step_fn(state, tokens, seq_lens)
    loss = float(loss)
    assert np.isfinite(loss), loss
    if is_coordinator():
        print(f"dryrun OK (multihost train dp(DCN)=2 x tp(ICI)=2): loss={loss:.4f}")

    # ---- 2. per-host tp-sharded serving replica ------------------------
    from k8s_llm_scheduler_tpu.engine.local import build_local_backend
    from k8s_llm_scheduler_tpu.types import DecisionSource, NodeMetrics, PodSpec

    serve_cfg = LlamaConfig(
        name="dryrun-mh-serve", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=4096,
        rope_theta=10000.0, dtype=jnp.float32, tie_embeddings=True,
    )
    backend = build_local_backend(
        cfg=serve_cfg, mesh_axes={"tp": 2}, devices=jax.local_devices()[:2],
        max_slots=2, num_pages=64, page_size=64,
        prefill_buckets=(512, 1024, 2048, 4096),
        chunk_steps=8, temperature=0.0, max_new_tokens=160,
        prefix_attn_impl="pallas",
    )
    try:
        nodes = [
            NodeMetrics(
                name=f"node-{i}", cpu_usage_percent=20.0 + 10 * i,
                memory_usage_percent=30.0, available_cpu_cores=8.0,
                available_memory_gb=32.0, pod_count=5, max_pods=110,
                labels={}, taints=(), conditions={"Ready": "True"},
            )
            for i in range(3)
        ]
        pod = PodSpec(
            name="mh-pod", namespace="default", cpu_request=0.1,
            memory_request=0.125, node_selector={}, tolerations=(),
            priority=0,
        )
        decision = backend.get_scheduling_decision(pod, nodes)
        assert decision.source is DecisionSource.LLM
        assert decision.selected_node in {n.name for n in nodes}

        # ---- 3. process-0-only bind ------------------------------------
        if is_coordinator():
            from k8s_llm_scheduler_tpu.cluster.fake import FakeCluster, FakeNode
            from k8s_llm_scheduler_tpu.cluster.interface import RawPod

            cluster = FakeCluster()
            for n in nodes:
                cluster.add_node(FakeNode(n.name))
            cluster.add_pod(RawPod(
                name="mh-pod", namespace="default",
                scheduler_name="ai-llama-scheduler",
                container_requests=({"cpu": "100m", "memory": "128Mi"},),
            ))
            ok = cluster.bind_pod_to_node("mh-pod", "default", decision.selected_node)
            assert ok
            print(
                f"dryrun OK (multihost serving, replica/host, tp=2, "
                f"coordinator-only bind): node={decision.selected_node}"
            )
        else:
            print(f"worker {process_id}: replica decision computed, no bind")

        # ---- 4. CROSS-HOST decision serving (sched/replica.py) ---------
        # The worker serves its replica over the decision-RPC transport;
        # the coordinator fans a burst of leaders out round-robin across
        # [its own backend, the worker's] — decisions EXECUTE on both
        # processes (the round-3 gap: workers had weights but no way to
        # receive work).
        import dataclasses as _dc

        from jax.experimental import multihost_utils

        from k8s_llm_scheduler_tpu.sched.replica import (
            FanoutBackend,
            ReplicaClient,
            ReplicaServer,
        )

        # The worker binds an OS-assigned port and publishes it through a
        # collective (a pre-agreed port races the Gloo/app ephemeral
        # binds in this multi-process harness; on real pods the same
        # allgather pattern removes any need for port coordination).
        # NOTE: no collective may be OUTSTANDING while the worker serves —
        # a pending barrier blocks the worker's device execution, so the
        # remote decision can never run (measured as a deadlock ->
        # coordinator timeout). The port allgather completes before
        # serving starts; completion is signaled through the replica
        # protocol itself (served-count poll), not a barrier.
        import time as _time

        # Coordinator-done sentinel: a FILE, not a collective — a barrier
        # would park the worker's device execution while it must still
        # serve decisions (measured deadlock; see module notes). Keyed on
        # the shared coordinator port, same host by construction. Stale
        # sentinels from a crashed earlier run are cleared BEFORE the port
        # allgather: both processes leave that barrier together, and a
        # worker polling a stale file would close before serving.
        done_path = Path(f"/tmp/dryrun_mh_done_{port}")
        if is_coordinator():
            done_path.unlink(missing_ok=True)
        server = None
        if not is_coordinator():
            server = ReplicaServer(backend, host="127.0.0.1", port=0)
        ports = multihost_utils.process_allgather(
            np.int32(server.port if server else 0)
        )
        if not is_coordinator():
            # >= 1: health-aware fanout probes the remote replica at least
            # once; the split beyond that depends on observed latencies.
            # Closing only after the coordinator's done-sentinel guarantees
            # no in-flight decision races the shutdown.
            deadline = _time.monotonic() + 300
            while (
                not done_path.exists() and _time.monotonic() < deadline
            ):
                _time.sleep(0.05)
            server.close()
            assert server.served >= 1, f"worker served {server.served}"
            print(
                f"dryrun OK (cross-host serving): worker {process_id} "
                f"served {server.served} decisions via replica RPC"
            )
        else:
            client = ReplicaClient("127.0.0.1", int(ports[1]))
            fan = FanoutBackend([backend, client])
            try:
                for i in range(4):
                    pod_i = _dc.replace(pod, name=f"mh-pod-{i}",
                                        cpu_request=0.1 + 0.01 * i)
                    d = fan.get_scheduling_decision(pod_i, nodes)
                    assert d.selected_node in {n.name for n in nodes}
                # health-aware dispatch: exact split depends on observed
                # latencies; the cross-host proof is that BOTH processes
                # executed decisions
                assert all(n > 0 for n in fan.routed), fan.routed
                print(
                    "dryrun OK (cross-host serving): coordinator fanned "
                    f"4 decisions routed={fan.routed} over [local, worker]"
                )
            finally:
                client.close()
                done_path.touch()
    finally:
        backend.close()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _attempt() -> tuple[int, list[str]]:
    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
    )
    procs = [
        subprocess.Popen(
            [sys.executable, __file__, "--process-id", str(i), "--port", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    rc = 0
    for i, (p, out) in enumerate(zip(procs, outs)):
        print(f"--- process {i} (rc={p.returncode}) ---")
        print(out[-2000:])
        rc |= p.returncode
    return rc, outs


def parent() -> int:
    rc, outs = _attempt()
    if rc != 0 and any("in use" in o.lower() for o in outs):
        # free-port probe is racy (the socket closes before the
        # coordinator binds it) — one retry on a fresh port
        print("coordinator port raced, retrying on a fresh port")
        rc, outs = _attempt()
    if rc == 0:
        assert "multihost train" in outs[0] and "coordinator-only bind" in outs[0]
        assert "no bind" in outs[1]
        # cross-host serving: decisions executed on BOTH processes
        assert "coordinator fanned 4 decisions routed=" in outs[0], outs[0][-500:]
        assert "decisions via replica RPC" in outs[1], outs[1][-500:]
        print("dryrun_multihost: ALL OK")
    return rc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--port", type=int, default=None)
    args = ap.parse_args()
    if args.process_id is None:
        raise SystemExit(parent())
    child(args.process_id, args.port)
