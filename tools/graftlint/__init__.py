"""graftlint — the repo's pluggable AST static-analysis framework.

Where tools/py310_lint.py guards one regression class (3.11+-only APIs on
a 3.10 floor) with regexes, graftlint guards the two hazard classes the
test suite can only catch probabilistically:

- **concurrency** discipline across the 18+ threading/asyncio lock sites
  (locks held across ``await``, blocking calls inside coroutines, writes
  to lock-guarded attributes that skip the lock) — the exact failure
  modes PRs 2-4 kept fixing post-hoc (prewarm advisory races, the
  PhaseRecorder snapshot race);
- **JAX purity** in the jit'd inference path (host syncs inside traced
  code, Python-side mutation under a trace, donated buffers reused after
  donation) — each one a silent per-call device round trip or a
  corrupted buffer.

Design: rules are AST visitors registered in RULES (rules/ package); the
runner parses each file once and hands the tree to every selected rule.
Suppress a single finding with a trailing

    # graftlint: ok[rule-id] — one-line justification

pragma (the justification is REQUIRED by the repo-sweep test). The py310
family keeps its historical ``# py310-ok`` pragma as an alias.

Entry points: ``python -m tools.graftlint`` (exit 0 clean / 1 findings /
2 internal error), ``cli lint``, and tests/test_graftlint.py which pins a
fixture corpus per rule plus a repo-wide clean run.
"""

from tools.graftlint.core import (  # noqa: F401
    Finding,
    LintRule,
    RuleViolationError,
    iter_repo_files,
    lint_file,
    lint_text,
    run_repo,
)
from tools.graftlint.rules import RULES, rules_by_selector  # noqa: F401
