"""``python -m tools.graftlint [paths...] [--rules a,b] [--format jsonl]``

Exit codes: 0 = clean (suppressed findings allowed), 1 = unsuppressed
findings, 2 = usage or internal error. The same runner backs ``cli lint``
and the pytest gate (tests/test_graftlint.py::test_repo_is_clean).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

# Allow `python tools/graftlint` and `python -m tools.graftlint` from the
# repo root even when the root is not on sys.path.
_ROOT = Path(__file__).resolve().parent.parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

from tools.graftlint.core import RuleViolationError, run_repo  # noqa: E402
from tools.graftlint.rules import RULES, rules_by_selector  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST concurrency & JAX-purity analyzer for this repo",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files to lint (default: the whole first-party tree)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids or families (default: all)",
    )
    parser.add_argument(
        "--format", choices=("human", "jsonl"), default="human",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.id:24s} [{rule.family}] {rule.description}")
        return 0

    try:
        selectors = (
            [s.strip() for s in args.rules.split(",") if s.strip()]
            if args.rules else None
        )
        rules = rules_by_selector(selectors)
        paths = args.paths or None
        if paths:
            missing = [p for p in paths if not p.is_file()]
            if missing:
                print(f"graftlint: no such file(s): {missing}", file=sys.stderr)
                return 2
        report = run_repo(rules, paths=paths)
    except RuleViolationError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.format == "jsonl":
        for f in report.findings:
            print(f.to_json())
    else:
        for f in report.findings:
            print(f.human(), file=sys.stderr)
        if report.findings:
            print(
                f"graftlint: {len(report.findings)} finding(s) in "
                f"{report.files_scanned} file(s) "
                f"({len(report.suppressed)} suppressed)",
                file=sys.stderr,
            )
        else:
            print(
                f"graftlint: OK ({report.files_scanned} files, "
                f"{len(report.suppressed)} suppressed finding(s))"
            )
    return 1 if report.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
