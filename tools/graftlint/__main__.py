"""``python -m tools.graftlint [paths...] [--rules a,b] [--format jsonl]``

Exit codes: 0 = clean (suppressed findings allowed), 1 = unsuppressed
findings, 2 = usage or internal error. The same runner backs ``cli lint``
and the pytest gate (tests/test_graftlint.py::test_repo_is_clean).

``--changed [REF]`` lints only the first-party files that differ from
REF (default HEAD) plus untracked ones — the pre-commit shape. The
interprocedural graph is still built over the WHOLE tree (reachability
must not depend on which files you are reporting on); only the findings
are filtered to the changed set.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

# Allow `python tools/graftlint` and `python -m tools.graftlint` from the
# repo root even when the root is not on sys.path.
_ROOT = Path(__file__).resolve().parent.parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

from tools.graftlint.core import (  # noqa: E402
    REPO_ROOT,
    RuleViolationError,
    iter_repo_files,
    run_repo,
)
from tools.graftlint.rules import RULES, rules_by_selector  # noqa: E402


def changed_files(ref: str, root: Path | None = None) -> list[Path]:
    """First-party files that differ from `ref`, plus untracked ones,
    intersected with the scan set (deleted files drop out via is_file)."""
    root = root or REPO_ROOT
    names: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True, check=False,
        )
        if proc.returncode != 0:
            raise RuleViolationError(
                f"--changed: `{' '.join(cmd)}` failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        names.update(line.strip() for line in proc.stdout.splitlines() if line.strip())
    scan_set = {str(p.relative_to(root)): p for p in iter_repo_files(root)}
    return [scan_set[n] for n in sorted(names) if n in scan_set]


def list_rules_grouped() -> str:
    """The rule catalog grouped by family, one line per rule."""
    by_family: dict[str, list] = {}
    for rule in RULES:
        by_family.setdefault(rule.family, []).append(rule)
    lines: list[str] = []
    for family in sorted(by_family):
        lines.append(f"{family}:")
        for rule in sorted(by_family[family], key=lambda r: r.id):
            lines.append(f"  {rule.id:28s} {rule.description}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST concurrency & JAX-purity analyzer for this repo",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files to lint (default: the whole first-party tree)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids or families (default: all)",
    )
    parser.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="REF",
        help="lint only first-party files differing from REF (default "
        "HEAD) plus untracked ones — the pre-commit mode",
    )
    parser.add_argument(
        "--format", choices=("human", "jsonl"), default="human",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog grouped by family",
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="ignore and do not write the on-disk analysis cache",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(list_rules_grouped())
        return 0

    try:
        selectors = (
            [s.strip() for s in args.rules.split(",") if s.strip()]
            if args.rules else None
        )
        rules = rules_by_selector(selectors)
        paths = args.paths or None
        if args.changed is not None:
            if paths:
                print(
                    "graftlint: --changed and explicit paths are mutually "
                    "exclusive", file=sys.stderr,
                )
                return 2
            paths = changed_files(args.changed)
            if not paths:
                print(f"graftlint: OK (no first-party files differ from "
                      f"{args.changed})")
                return 0
        if paths:
            missing = [p for p in paths if not p.is_file()]
            if missing:
                print(f"graftlint: no such file(s): {missing}", file=sys.stderr)
                return 2
        report = run_repo(rules, paths=paths, use_cache=not args.no_cache)
    except RuleViolationError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.format == "jsonl":
        for f in report.findings:
            print(f.to_json())
    else:
        for f in report.findings:
            print(f.human(), file=sys.stderr)
        if report.findings:
            print(
                f"graftlint: {len(report.findings)} finding(s) in "
                f"{report.files_scanned} file(s) "
                f"({len(report.suppressed)} suppressed)",
                file=sys.stderr,
            )
        else:
            print(
                f"graftlint: OK ({report.files_scanned} files, "
                f"{len(report.suppressed)} suppressed finding(s))"
            )
    return 1 if report.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
