"""graftlint framework core: file iteration, pragma handling, the rule
base class, and the runner.

A rule is one hazard class with a stable kebab-case id (``lock-across-
await``). The runner parses each file ONCE, builds a FileContext (source
lines + per-line pragma table + AST), and hands it to every selected
rule; findings whose line carries a matching pragma are filtered into
the report's ``suppressed`` list instead of ``findings``.

Pragma grammar (one comment, end of the offending line)::

    # graftlint: ok[rule-id] — justification text
    # graftlint: ok[rule-a, rule-b] — one pragma may cover several rules

The justification is mandatory: a bare ``ok[rule-id]`` does NOT
suppress (the finding survives, annotated) — a silenced checker with no
recorded reason is how suppressions rot. The py310 family additionally
honors the historical ``# py310-ok`` pragma (with or without a reason)
so every existing call site keeps working.

Interprocedural rules see the whole repo through ``ctx.repo`` — a
:class:`tools.graftlint.repograph.RepoGraph` built once per run (and
served from the content-hash cache on disk). The graph-construction
policy keeps fixtures self-contained:

- ``run_repo`` over the first-party tree (or any subset of it, e.g.
  ``--changed``) builds ONE whole-tree graph and lints the requested
  files against it — cross-module reachability is always computed over
  the full repo, never just the files being reported on;
- explicit paths OUTSIDE the scan set (the fixture corpus, ad-hoc
  files) each get a single-file graph, so a deliberately-bad fixture
  can never borrow innocence (or guilt) from its neighbors;
- ``lint_text`` builds a single-file graph lazily on first
  ``ctx.repo`` access.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable, Iterator

from tools.graftlint.repograph import CACHE_BASENAME, RepoGraph, iter_file_funcs

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

# Directories holding first-party Python (same set tools/py310_lint.py
# established), minus the lint machinery itself and its fixture corpus:
# rule pattern tables and deliberately-bad fixtures must not trip the
# repo-wide clean gate.
SCAN_DIRS = ("k8s_llm_scheduler_tpu", "tests", "tools")
SCAN_FILES = ("bench.py", "__graft_entry__.py")
EXCLUDE_PARTS = (
    ("tools", "graftlint"),
    ("tests", "fixtures", "graftlint"),
)
EXCLUDE_FILES = (("tools", "py310_lint.py"),)

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*ok\[(?P<ids>[a-z0-9_,\-\s]+)\]\s*(?P<why>\S.*)?$"
)
PY310_PRAGMA = "# py310-ok"


class RuleViolationError(Exception):
    """Internal graftlint failure (bad selector, broken rule) — exit 2."""


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    def human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)


@dataclasses.dataclass
class Pragma:
    ids: frozenset[str]
    justified: bool


class FileContext:
    """Everything a rule needs about one file, computed once."""

    def __init__(self, name: str, text: str, repo: RepoGraph | None = None) -> None:
        self.name = name
        self.text = text
        self._repo = repo
        self.lines = text.splitlines()
        self.pragmas: dict[int, Pragma] = {}
        for lineno, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if m:
                ids = frozenset(
                    t.strip() for t in m.group("ids").split(",") if t.strip()
                )
                self.pragmas[lineno] = Pragma(ids, bool(m.group("why")))
            elif PY310_PRAGMA in line:
                # historical alias: suppresses the whole py310 family
                self.pragmas[lineno] = Pragma(frozenset(("py310",)), True)
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as exc:
            self.parse_error = exc
        # memoized whole-tree traversals: every rule iterates the same
        # nodes, and N rules x M files of repeated ast.walk/iter_funcs
        # dominated the full-repo wall clock (the <10s fast-tier budget)
        self._all_nodes: list[ast.AST] | None = None
        self._functions: list | None = None
        self._graph_funcs: list | None = None

    def all_nodes(self) -> list[ast.AST]:
        """Flat ast.walk of the whole tree, computed once per file."""
        if self._all_nodes is None:
            self._all_nodes = list(ast.walk(self.tree))
        return self._all_nodes

    def functions(self) -> list:
        """[(func def, owning class | None), ...], computed once per file."""
        if self._functions is None:
            self._functions = list(iter_funcs(self.tree))
        return self._functions

    @property
    def repo(self) -> RepoGraph:
        """The whole-repo call graph (or, for a standalone file, a graph
        of just this file). Shared across every file of a run_repo pass;
        rules key reachability questions on `ctx.gqual(local_qual)`."""
        if self._repo is None:
            self._repo = RepoGraph.from_texts({self.name: self.text})
        return self._repo

    def gqual(self, local_qual: str) -> str:
        """This file's `local_qual` as a repo-global function id."""
        return f"{self.name}::{local_qual}"

    def graph_funcs(self) -> list:
        """[(local qual, def node, owning class name | None), ...] using
        the SAME qual scheme as the repo index, so a rule can pair the
        live AST node with its graph entry. Memoized per file."""
        if self._graph_funcs is None:
            self._graph_funcs = list(iter_file_funcs(self.tree))
        return self._graph_funcs

    def finding(
        self, rule: "LintRule", node: ast.AST | int, message: str
    ) -> Finding:
        if isinstance(node, int):
            line, col = node, 0
        else:
            line, col = node.lineno, node.col_offset
        snippet = self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        return Finding(rule.id, self.name, line, col, message, snippet)


class LintRule:
    """One hazard class. Subclasses set `id`, `family`, `description` and
    implement check(ctx) -> Iterable[Finding]. AST rules may assume
    ctx.tree is not None (the runner reports parse errors itself and
    skips AST rules for broken files)."""

    id: str = ""
    family: str = ""
    description: str = ""
    needs_ast: bool = True

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclasses.dataclass
class LintReport:
    findings: list[Finding]
    suppressed: list[Finding]
    files_scanned: int

    @property
    def clean(self) -> bool:
        return not self.findings


def iter_repo_files(root: Path | None = None) -> list[Path]:
    root = root or REPO_ROOT
    out: list[Path] = []
    for d in SCAN_DIRS:
        base = root / d
        if base.is_dir():
            out.extend(sorted(base.rglob("*.py")))
    for f in SCAN_FILES:
        p = root / f
        if p.is_file():
            out.append(p)

    def excluded(p: Path) -> bool:
        rel = p.relative_to(root).parts
        for parts in EXCLUDE_PARTS:
            if rel[: len(parts)] == parts:
                return True
        return rel in EXCLUDE_FILES

    return [p for p in out if not excluded(p)]


def _apply_pragmas(
    ctx: FileContext, raw: list[Finding]
) -> tuple[list[Finding], list[Finding]]:
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        pragma = ctx.pragmas.get(f.line)
        hit = pragma is not None and (
            f.rule in pragma.ids or _family_of(f.rule) in pragma.ids
        )
        if hit and pragma.justified:
            suppressed.append(f)
        elif hit:
            f.message += " (pragma present but missing a justification)"
            findings.append(f)
        else:
            findings.append(f)
    return findings, suppressed


_FAMILIES: dict[str, str] = {}


def _family_of(rule_id: str) -> str:
    return _FAMILIES.get(rule_id, "")


def lint_text(
    text: str, name: str, rules: Iterable[LintRule],
    repo: RepoGraph | None = None,
) -> LintReport:
    ctx = FileContext(name, text, repo=repo)
    raw: list[Finding] = []
    rules = list(rules)
    for rule in rules:
        _FAMILIES.setdefault(rule.id, rule.family)
    if ctx.parse_error is not None:
        err = ctx.parse_error
        raw.append(
            Finding(
                "parse-error", name, err.lineno or 1, (err.offset or 1) - 1,
                f"file does not parse: {err.msg}",
            )
        )
        rules = [r for r in rules if not r.needs_ast]
    for rule in rules:
        try:
            raw.extend(rule.check(ctx))
        except Exception as exc:  # a broken rule must be loud, not silent
            raise RuleViolationError(
                f"rule {rule.id} crashed on {name}: {exc!r}"
            ) from exc
    raw.sort(key=lambda f: (f.line, f.col, f.rule))
    findings, suppressed = _apply_pragmas(ctx, raw)
    return LintReport(findings, suppressed, files_scanned=1)


def lint_file(
    path: Path, rules: Iterable[LintRule], root: Path | None = None,
    repo: RepoGraph | None = None,
) -> LintReport:
    root = root or REPO_ROOT
    try:
        name = str(path.resolve().relative_to(root))
    except ValueError:
        name = str(path)
    return lint_text(path.read_text(), name, rules, repo=repo)


def build_repo_graph(
    root: Path | None = None,
    files: Iterable[Path] | None = None,
    use_cache: bool = True,
) -> RepoGraph:
    """The whole-tree interprocedural graph, content-hash cached at
    `<root>/.graftlint_cache.json` (gitignored; safe to delete any time
    — it only makes the next run cold)."""
    root = root or REPO_ROOT
    files = list(files) if files is not None else iter_repo_files(root)
    cache_path = (root / CACHE_BASENAME) if use_cache else None
    return RepoGraph.build(files, root, cache_path=cache_path)


def run_repo(
    rules: Iterable[LintRule],
    root: Path | None = None,
    paths: Iterable[Path] | None = None,
    use_cache: bool = True,
) -> LintReport:
    """Lint explicit `paths`, or the whole first-party tree.

    Graph policy: paths inside the scan set are linted against the
    WHOLE-TREE graph (reachability must not depend on which files you
    asked to see — `--changed` linting one file still knows the jit
    roots two modules away); paths outside it (fixtures) each get a
    single-file graph so deliberately-bad corpora stay self-contained.
    """
    rules = list(rules)
    root = root or REPO_ROOT
    repo_files = iter_repo_files(root)
    files = list(paths) if paths is not None else repo_files
    in_scan_set = {p.resolve() for p in repo_files}
    shared: RepoGraph | None = None
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    for path in files:
        repo = None
        if path.resolve() in in_scan_set:
            if shared is None:
                shared = build_repo_graph(root, repo_files, use_cache=use_cache)
            repo = shared
        rep = lint_file(path, rules, root=root, repo=repo)
        findings.extend(rep.findings)
        suppressed.extend(rep.suppressed)
    return LintReport(findings, suppressed, files_scanned=len(files))


# ---------------------------------------------------------------- AST utils
def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_funcs(
    tree: ast.Module,
) -> Iterator[tuple[ast.FunctionDef | ast.AsyncFunctionDef, ast.ClassDef | None]]:
    """Every function/method in the module with its owning class (None for
    module-level and nested functions)."""

    def walk(node: ast.AST, cls: ast.ClassDef | None) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child)
            else:
                yield from walk(child, cls)

    yield from walk(tree, None)


def body_walk(func: ast.AST) -> Iterator[ast.AST]:
    """ast.walk over a function body WITHOUT descending into nested
    function/class definitions (their hazards are their own scope's)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))
