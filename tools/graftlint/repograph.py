"""Whole-repo interprocedural engine: module/symbol index, call graph,
and the reachability/dataflow API the rule families build on.

graftlint v1 analyzed one file at a time; anything cross-module rode an
ad-hoc name prepass (`_global_jit_names`) re-scanning the tree per
process. The contracts the repo actually cares about are cross-module
and path-shaped — "is this function reachable from a jit root that
engine/engine.py wrapped around a models/llama.py def", "does every
swap path also reach a generation bump" — so v2 builds ONE repo-wide
index and answers those questions from it.

Three layers:

1. **ModuleIndex** — everything the graph needs about one file, extracted
   in a single AST pass and JSON-serializable: the function table
   (qualified defs, async-ness, decorators), per-function call sites
   (dotted names + line numbers + canonical-writer flags), per-function
   AugAssign attribute evidence (``self.prefix_epoch += 1`` is epoch-bump
   evidence for the protocol family), import bindings, class tables
   (bases, attribute types inferred from ``self.x = ClassName(...)``),
   local/param type bindings, ``jax.jit``/``shard_map`` wrap sites (with
   static/donate positions, seeing through ``functools.partial``),
   PartitionSpec literal axes, and module-level string-tuple constants
   (the MESH_AXES declaration reads through this).

2. **RepoGraph** — the merged view plus call resolution. Every function
   gets a global qualname ``relpath::Class.method``. A call site resolves
   under one of two dispatch policies:

   - ``strict``: bare names to same-module defs or followed through the
     import table into the defining module; ``self.x()``/``cls.x()`` to
     the owning class (then bases); ``obj.m()`` through the receiver's
     inferred type (parameter annotation, ``x = ClassName(...)`` local
     binding, or a class attribute typed in ``__init__``). Unresolvable
     receivers produce NO edge — strict never guesses, so "reachable
     from a jit root" stays false-positive-poor.
   - ``bare``: strict, plus unresolved ``obj.m()`` attribute calls link
     to every repo def named ``m`` (common container-method names are
     blocked). Generous linking is the right polarity for the protocol
     family, where reaching MORE evidence can only suppress findings.

3. **Reachability API** — ``reachable(seeds, dispatch=...)`` (memoized
   per seed-set) and ``reaches(start, pred, dispatch=...)`` ("from this
   function, is a call site / AugAssign matching `pred` reachable?").

The on-disk cache (``.graftlint_cache.json``, content-hash-keyed per
module) makes the index incremental: an unchanged file is never
re-parsed, so the full-repo `cli lint` keeps its <10s fast-tier budget
and a single-file edit re-indexes exactly that file.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from pathlib import Path
from typing import Callable, Iterable, Iterator

INDEX_VERSION = 2
CACHE_BASENAME = ".graftlint_cache.json"

_JIT_WRAPPERS = ("jax.jit", "jit", "pjit", "jax.pjit")
_SHMAP_WRAPPERS = (
    "shard_map", "jax.shard_map", "shard_map_compat",
    "jax.experimental.shard_map.shard_map",
)
_PARTIAL_NAMES = ("partial", "functools.partial")

# Attribute-call names too generic to bare-link: every container and a
# handful of repo-wide conventions (start/stop/close/run appear on dozens
# of unrelated classes; linking them would weld the graph into one blob).
_BARE_DISPATCH_BLOCKLIST = frozenset({
    "append", "extend", "add", "update", "pop", "remove", "insert", "get",
    "items", "keys", "values", "setdefault", "clear", "copy", "join",
    "split", "strip", "encode", "decode", "format", "read", "write",
    "close", "open", "start", "stop", "run", "put", "send", "recv",
    "acquire", "release", "wait", "notify", "set", "result", "done",
    "submit", "cancel", "sort", "index", "count", "popitem", "discard",
})


def dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_name(name: str) -> bool:
    return name in _JIT_WRAPPERS or name in _SHMAP_WRAPPERS


def _const_ints(keywords: list[ast.keyword], kw: str) -> list[int]:
    for k in keywords:
        if k.arg != kw:
            continue
        if isinstance(k.value, (ast.Tuple, ast.List)):
            return [
                e.value for e in k.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            ]
        if isinstance(k.value, ast.Constant) and isinstance(k.value.value, int):
            return [k.value.value]
    return []


def _const_strs(keywords: list[ast.keyword], kw: str) -> list[str]:
    for k in keywords:
        if k.arg != kw:
            continue
        if isinstance(k.value, (ast.Tuple, ast.List)):
            return [
                e.value for e in k.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
        if isinstance(k.value, ast.Constant) and isinstance(k.value.value, str):
            return [k.value.value]
    return []


def _is_canonical_writer(call: ast.Call, name: str) -> bool:
    """A call site that serializes into a replay-compared / digested
    artifact: the named canonical_* writers, json.dump(s) with
    sort_keys=True (the repo's canonical-JSON convention), and hashlib
    digest constructors fed data."""
    last = name.rsplit(".", 1)[-1]
    if last in (
        "canonical_bytes", "canonical_chaos_bytes",
        "canonical_blackbox_bytes", "save_trace",
    ):
        return True
    if name in ("json.dumps", "json.dump"):
        return any(
            kw.arg == "sort_keys"
            and isinstance(kw.value, ast.Constant) and kw.value.value is True
            for kw in call.keywords
        )
    if name.startswith("hashlib.") and last in (
        "blake2b", "sha256", "sha1", "md5", "blake2s",
    ):
        return bool(call.args)
    return False


class FuncEntry:
    """One function/method in the index (JSON round-trippable)."""

    __slots__ = (
        "qual", "name", "cls", "lineno", "is_async", "parent",
        "jit_decorated", "calls", "aug_attrs", "var_types",
    )

    def __init__(
        self, qual: str, name: str, cls: str | None, lineno: int,
        is_async: bool, parent: str | None, jit_decorated: bool,
        calls: list[dict], aug_attrs: list[str], var_types: dict[str, str],
    ) -> None:
        self.qual = qual
        self.name = name
        self.cls = cls
        self.lineno = lineno
        self.is_async = is_async
        self.parent = parent
        self.jit_decorated = jit_decorated
        # calls: [{"n": dotted, "l": lineno, "w": canonical-writer flag}]
        self.calls = calls
        self.aug_attrs = aug_attrs
        self.var_types = var_types

    def to_json(self) -> dict:
        return {
            "qual": self.qual, "name": self.name, "cls": self.cls,
            "lineno": self.lineno, "is_async": self.is_async,
            "parent": self.parent, "jit_decorated": self.jit_decorated,
            "calls": self.calls, "aug_attrs": self.aug_attrs,
            "var_types": self.var_types,
        }

    @classmethod
    def from_json(cls, d: dict) -> "FuncEntry":
        return cls(
            d["qual"], d["name"], d["cls"], d["lineno"], d["is_async"],
            d["parent"], d["jit_decorated"], d["calls"], d["aug_attrs"],
            d["var_types"],
        )


class ModuleIndex:
    """Everything the graph needs about one module, one AST pass."""

    __slots__ = (
        "path", "functions", "classes", "imports", "jit_wraps",
        "jit_assign_targets", "pspec_names", "str_tuples",
    )

    def __init__(self, path: str) -> None:
        self.path = path
        self.functions: dict[str, FuncEntry] = {}   # local qual -> entry
        # class name -> {"bases": [...], "methods": [...], "attrs": {a: T}}
        self.classes: dict[str, dict] = {}
        self.imports: dict[str, str] = {}           # local name -> source
        # [{"wrapped": bare, "target": dotted-or-"", "lineno": int,
        #   "static_argnums": [...], "static_argnames": [...],
        #   "donate_argnums": [...], "offset": int, "site_kws": [...],
        #   "partial_kws": [...]}]
        self.jit_wraps: list[dict] = []
        self.jit_assign_targets: list[str] = []
        self.str_tuples: dict[str, list[str]] = {}
        # local names bound to jax.sharding.PartitionSpec ("P", ...)
        self.pspec_names: list[str] = []

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, path: str, tree: ast.Module) -> "ModuleIndex":
        idx = cls(path)
        idx._imports(tree)
        idx._module_level(tree)
        idx._functions(tree)
        return idx

    def _imports(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    src = f"{node.module}.{a.name}"
                    self.imports[a.asname or a.name] = src
                    if src == "jax.sharding.PartitionSpec":
                        self.pspec_names.append(a.asname or a.name)
        if "PartitionSpec" not in self.pspec_names:
            self.pspec_names.append("PartitionSpec")

    def _module_level(self, tree: ast.Module) -> None:
        for node in tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                strs = [
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                if strs and len(strs) == len(node.value.elts):
                    self.str_tuples[t.id] = strs

    @staticmethod
    def _jit_wrap_record(call: ast.Call, target: str) -> dict | None:
        """A `jax.jit(fn, ...)` / `shard_map(fn, ...)` value site, seeing
        through functools.partial; None for anything else."""
        name = dotted(call.func)
        if not _is_jit_name(name) or not call.args:
            return None
        wrapped = call.args[0]
        offset = 0
        partial_kws: list[str] = []
        if isinstance(wrapped, ast.Call) and dotted(wrapped.func) in _PARTIAL_NAMES \
                and wrapped.args:
            offset = len(wrapped.args) - 1
            partial_kws = [kw.arg for kw in wrapped.keywords if kw.arg]
            wrapped = wrapped.args[0]
        bare = dotted(wrapped)
        bare = bare.rsplit(".", 1)[-1] if bare else ""
        if not bare:
            return None
        return {
            "wrapped": bare,
            "target": target,
            "lineno": call.lineno,
            "static_argnums": _const_ints(call.keywords, "static_argnums"),
            "static_argnames": _const_strs(call.keywords, "static_argnames"),
            "donate_argnums": _const_ints(call.keywords, "donate_argnums"),
            "offset": offset,
            "site_kws": [kw.arg for kw in call.keywords if kw.arg],
            "partial_kws": partial_kws,
        }

    def _functions(self, tree: ast.Module) -> None:
        idx = self

        def jit_decorator(dec: ast.AST) -> bool:
            if _is_jit_name(dotted(dec)):
                return True
            if isinstance(dec, ast.Call):
                name = dotted(dec.func)
                if _is_jit_name(name):
                    return True
                if name in _PARTIAL_NAMES and dec.args:
                    return _is_jit_name(dotted(dec.args[0]))
            return False

        def extract_func(
            func: ast.FunctionDef | ast.AsyncFunctionDef,
            cls_name: str | None, parent: str | None,
        ) -> FuncEntry:
            qual = func.name if cls_name is None else f"{cls_name}.{func.name}"
            if parent is not None:
                qual = f"{parent}.<locals>.{func.name}"
            calls: list[dict] = []
            aug_attrs: list[str] = []
            var_types: dict[str, str] = {}
            for arg in (
                func.args.posonlyargs + func.args.args + func.args.kwonlyargs
            ):
                ann = arg.annotation
                if ann is not None:
                    ann_name = dotted(ann)
                    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                        ann_name = ann.value.strip('"')
                    if ann_name:
                        var_types[arg.arg] = ann_name
            # one body walk, not descending into nested defs
            stack: list[ast.AST] = list(ast.iter_child_nodes(func))
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef, ast.Lambda)):
                    continue
                if isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if name:
                        rec = {"n": name, "l": node.lineno}
                        if _is_canonical_writer(node, name):
                            rec["w"] = True
                        calls.append(rec)
                elif isinstance(node, ast.AugAssign):
                    t = node.target
                    if isinstance(t, ast.Attribute):
                        aug_attrs.append(t.attr)
                    elif isinstance(t, ast.Name):
                        aug_attrs.append(t.id)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    callee = dotted(node.value.func)
                    # x = ClassName(...) binds x's receiver type (the
                    # CapWord convention is the signal; function calls
                    # stay untyped — strict dispatch never guesses)
                    if callee and callee.rsplit(".", 1)[-1][:1].isupper():
                        var_types.setdefault(node.targets[0].id, callee)
                stack.extend(ast.iter_child_nodes(node))
            return FuncEntry(
                qual, func.name, cls_name, func.lineno,
                isinstance(func, ast.AsyncFunctionDef), parent,
                any(jit_decorator(d) for d in func.decorator_list),
                calls, aug_attrs, var_types,
            )

        def walk(node: ast.AST, cls_name: str | None, parent: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    entry = extract_func(child, cls_name, parent)
                    idx.functions.setdefault(entry.qual, entry)
                    walk(child, cls_name, entry.qual)
                elif isinstance(child, ast.ClassDef):
                    bases = [dotted(b) for b in child.bases if dotted(b)]
                    methods = [
                        n.name for n in child.body
                        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    ]
                    attrs: dict[str, str] = {}
                    for sub in ast.walk(child):
                        # self.<attr> = ClassName(...) typed-attr inference
                        if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                            t = sub.targets[0]
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and isinstance(sub.value, ast.Call)
                            ):
                                callee = dotted(sub.value.func)
                                if callee and callee.rsplit(".", 1)[-1][:1].isupper():
                                    attrs.setdefault(t.attr, callee)
                        elif isinstance(sub, ast.AnnAssign) and isinstance(
                            sub.target, ast.Name
                        ):
                            ann = dotted(sub.annotation)
                            if ann:
                                attrs.setdefault(sub.target.id, ann)
                    idx.classes[child.name] = {
                        "bases": bases, "methods": methods, "attrs": attrs,
                    }
                    walk(child, child.name, None)
                else:
                    walk(child, cls_name, parent)

        walk(tree, None, None)

        # jit wrap sites anywhere (assignments keep their target name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                rec = self._jit_wrap_record(
                    node.value,
                    dotted(node.targets[0]) if len(node.targets) == 1 else "",
                )
                if rec is not None:
                    self.jit_wraps.append(rec)
                    if rec["target"]:
                        self.jit_assign_targets.append(rec["target"])
            elif isinstance(node, ast.Call):
                rec = self._jit_wrap_record(node, "")
                if rec is not None and not any(
                    w["lineno"] == rec["lineno"] and w["wrapped"] == rec["wrapped"]
                    for w in self.jit_wraps
                ):
                    self.jit_wraps.append(rec)

    # ------------------------------------------------------------- (de)ser
    def to_json(self) -> dict:
        return {
            "functions": {q: f.to_json() for q, f in self.functions.items()},
            "classes": self.classes,
            "imports": self.imports,
            "jit_wraps": self.jit_wraps,
            "jit_assign_targets": self.jit_assign_targets,
            "pspec_names": self.pspec_names,
            "str_tuples": self.str_tuples,
        }

    @classmethod
    def from_json(cls, path: str, d: dict) -> "ModuleIndex":
        idx = cls(path)
        idx.functions = {
            q: FuncEntry.from_json(f) for q, f in d["functions"].items()
        }
        idx.classes = d["classes"]
        idx.imports = d["imports"]
        idx.jit_wraps = d["jit_wraps"]
        idx.jit_assign_targets = d["jit_assign_targets"]
        idx.pspec_names = d["pspec_names"]
        idx.str_tuples = d["str_tuples"]
        return idx


def _module_dotted(relpath: str) -> str:
    """'k8s_llm_scheduler_tpu/engine/engine.py' -> dotted module path."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class RepoGraph:
    """The merged whole-repo view + call resolution + reachability."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleIndex] = {}      # relpath -> index
        self.by_module_dotted: dict[str, str] = {}     # dotted -> relpath
        self.funcs: dict[str, FuncEntry] = {}          # gqual -> entry
        self.func_module: dict[str, str] = {}          # gqual -> relpath
        self.by_bare: dict[str, list[str]] = {}        # bare -> [gqual]
        self.class_module: dict[str, list[str]] = {}   # class -> [relpath]
        # build stats for the cache test + `--stats`-style introspection
        self.indexed_files: list[str] = []             # re-parsed this build
        self.cached_files: list[str] = []              # served from cache
        self._edges_memo: dict[tuple[str, str], tuple[str, ...]] = {}
        self._reach_memo: dict[tuple[frozenset[str], str], frozenset[str]] = {}

    # ------------------------------------------------------------- build
    @classmethod
    def build(
        cls,
        files: Iterable[Path],
        root: Path,
        cache_path: Path | None = None,
    ) -> "RepoGraph":
        graph = cls()
        cache: dict = {}
        if cache_path is not None and cache_path.is_file():
            try:
                loaded = json.loads(cache_path.read_text())
                if loaded.get("version") == INDEX_VERSION:
                    cache = loaded.get("modules", {})
            except (OSError, ValueError):
                cache = {}
        fresh: dict[str, dict] = {}
        dirty = False
        for path in files:
            try:
                rel = str(path.resolve().relative_to(root))
            except ValueError:
                rel = str(path)
            try:
                text = path.read_text()
            except OSError:
                continue
            sha = hashlib.sha256(text.encode()).hexdigest()
            entry = cache.get(rel)
            if entry is not None and entry.get("sha") == sha:
                idx = ModuleIndex.from_json(rel, entry["index"])
                graph.cached_files.append(rel)
                fresh[rel] = entry
            else:
                try:
                    tree = ast.parse(text)
                except SyntaxError:
                    continue  # the runner reports parse errors itself
                idx = ModuleIndex.build(rel, tree)
                graph.indexed_files.append(rel)
                fresh[rel] = {"sha": sha, "index": idx.to_json()}
                dirty = True
            graph._add(idx)
        if cache_path is not None and (dirty or set(fresh) != set(cache)):
            graph._write_cache(cache_path, fresh)
        graph._finish()
        return graph

    @classmethod
    def from_texts(cls, texts: dict[str, str]) -> "RepoGraph":
        """In-memory build (lint_text / fixture snippets)."""
        graph = cls()
        for name, text in texts.items():
            try:
                tree = ast.parse(text)
            except SyntaxError:
                continue
            graph._add(ModuleIndex.build(name, tree))
            graph.indexed_files.append(name)
        graph._finish()
        return graph

    @staticmethod
    def _write_cache(cache_path: Path, modules: dict) -> None:
        payload = json.dumps(
            {"version": INDEX_VERSION, "modules": modules},
            sort_keys=True, separators=(",", ":"),
        )
        tmp = cache_path.with_name(cache_path.name + f".tmp{os.getpid()}")
        try:
            tmp.write_text(payload)
            os.replace(tmp, cache_path)  # graftlint: ok[rename-without-fsync] — disposable derived cache; a torn file fails the version check and rebuilds
        except OSError:
            # a read-only checkout must still lint; the cache is an
            # optimization, never a requirement
            try:
                tmp.unlink()
            except OSError:
                pass

    def _add(self, idx: ModuleIndex) -> None:
        self.modules[idx.path] = idx
        self.by_module_dotted[_module_dotted(idx.path)] = idx.path
        for qual, entry in idx.functions.items():
            g = f"{idx.path}::{qual}"
            self.funcs[g] = entry
            self.func_module[g] = idx.path
            self.by_bare.setdefault(entry.name, []).append(g)
        for cname in idx.classes:
            self.class_module.setdefault(cname, []).append(idx.path)

    def _finish(self) -> None:
        # deterministic iteration everywhere downstream
        for quals in self.by_bare.values():
            quals.sort()

    # -------------------------------------------------------- jit roots
    def jit_roots(self) -> frozenset[str]:
        """Every function that is a jit/shard_map root: decorated defs,
        wrapped names (strict resolution into the defining module via
        imports), and the bare-name fallback the engine's cross-module
        jit idiom needs (engine/engine.py jits models/llama.py defs that
        ride in through locals the AST can't type)."""
        memo = getattr(self, "_jit_roots", None)
        if memo is not None:
            return memo
        roots: set[str] = set()
        wrapped_bares: set[str] = set()
        for rel, idx in self.modules.items():
            for qual, entry in idx.functions.items():
                if entry.jit_decorated:
                    roots.add(f"{rel}::{qual}")
            for wrap in idx.jit_wraps:
                wrapped_bares.add(wrap["wrapped"])
        for bare in wrapped_bares:
            roots.update(self.by_bare.get(bare, ()))
        self._jit_roots = frozenset(roots)
        return self._jit_roots

    def steady_roots(self) -> frozenset[str]:
        """The persistent serving plane's declared steady-path functions
        (name contract: `*_steady`, or the ordered-io_callback bodies)."""
        memo = getattr(self, "_steady_roots", None)
        if memo is not None:
            return memo
        out = frozenset(
            g for g, e in self.funcs.items()
            if e.name.endswith("_steady")
            or e.name in ("_device_poll", "_device_push")
        )
        self._steady_roots = out
        return self._steady_roots

    # -------------------------------------------------------- resolution
    def _resolve_import(self, module_rel: str, name: str) -> list[str]:
        """Follow `name` through `module_rel`'s import table to defs."""
        idx = self.modules.get(module_rel)
        if idx is None:
            return []
        src = idx.imports.get(name)
        if not src:
            return []
        # src is "pkg.mod.symbol" or "pkg.mod"
        for cut in (src.rsplit(".", 1), (src, "")):
            mod_dotted, sym = cut if len(cut) == 2 else (cut[0], "")
            rel = self.by_module_dotted.get(mod_dotted)
            if rel is None:
                continue
            if sym:
                g = f"{rel}::{sym}"
                if g in self.funcs:
                    return [g]
                # imported class: constructor edge to __init__
                if sym in self.modules[rel].classes:
                    g = f"{rel}::{sym}.__init__"
                    return [g] if g in self.funcs else []
            return []
        return []

    def _class_method(self, cls_name: str, meth: str, home: str) -> list[str]:
        """`cls_name.meth` resolved in `home`'s import scope, walking
        base classes (by name) when the class itself lacks the method."""
        seen: set[str] = set()
        stack = [(cls_name, home)]
        while stack:
            cname, mod = stack.pop()
            cname = cname.rsplit(".", 1)[-1]
            if cname in seen:
                continue
            seen.add(cname)
            # resolve the class to its defining module(s)
            rels: list[str] = []
            idx = self.modules.get(mod)
            if idx is not None and cname in idx.classes:
                rels = [mod]
            elif idx is not None and cname in idx.imports:
                src = idx.imports[cname]
                mod_dotted, _, sym = src.rpartition(".")
                rel = self.by_module_dotted.get(mod_dotted)
                if rel is not None and sym in self.modules[rel].classes:
                    rels = [rel]
            else:
                rels = [
                    r for r in self.class_module.get(cname, [])
                ]
            for rel in rels:
                cinfo = self.modules[rel].classes.get(cname)
                if cinfo is None:
                    continue
                if meth in cinfo["methods"]:
                    g = f"{rel}::{cname}.{meth}"
                    if g in self.funcs:
                        return [g]
                for base in cinfo["bases"]:
                    stack.append((base, rel))
        return []

    def resolve_call(
        self, caller: str, callname: str, dispatch: str = "strict"
    ) -> list[str]:
        """Callee gquals for a `callname` call site inside `caller`."""
        rel = self.func_module.get(caller)
        if rel is None:
            return []
        entry = self.funcs[caller]
        idx = self.modules[rel]
        head, _, rest = callname.partition(".")

        if not rest:
            # bare call: enclosing-scope nested def, same-module def,
            # then the import table
            if entry.parent is not None:
                g = f"{rel}::{entry.parent}.<locals>.{callname}"
                if g in self.funcs:
                    return [g]
            for pref in (entry.qual + ".<locals>.",):
                g = f"{rel}::{pref}{callname}"
                if g in self.funcs:
                    return [g]
            g = f"{rel}::{callname}"
            if g in self.funcs:
                return [g]
            if callname in idx.classes:
                g = f"{rel}::{callname}.__init__"
                return [g] if g in self.funcs else []
            return self._resolve_import(rel, callname)

        meth = callname.rsplit(".", 1)[-1]
        if head in ("self", "cls") and entry.cls is not None:
            if "." not in rest:  # self.meth()
                hit = self._class_method(entry.cls, meth, rel)
                if hit:
                    return hit
            else:
                # self.attr.meth(): typed attribute inference
                attr = rest.rsplit(".", 1)[0]
                if "." not in attr:
                    cinfo = idx.classes.get(entry.cls, {})
                    atype = cinfo.get("attrs", {}).get(attr)
                    if atype:
                        hit = self._class_method(atype, meth, rel)
                        if hit:
                            return hit
        elif "." not in rest:
            # x.meth(): local/param type binding, module alias, or class
            recv_type = entry.var_types.get(head)
            if recv_type:
                hit = self._class_method(recv_type, meth, rel)
                if hit:
                    return hit
            if head in idx.classes:
                hit = self._class_method(head, meth, rel)
                if hit:
                    return hit
            src = idx.imports.get(head)
            if src:
                mod_rel = self.by_module_dotted.get(src)
                if mod_rel is not None:  # module alias: mod.fn()
                    g = f"{mod_rel}::{meth}"
                    if g in self.funcs:
                        return [g]
                else:
                    # imported class used as receiver type namespace
                    mod_dotted, _, sym = src.rpartition(".")
                    rel2 = self.by_module_dotted.get(mod_dotted)
                    if rel2 is not None and sym in self.modules[rel2].classes:
                        hit = self._class_method(sym, meth, rel2)
                        if hit:
                            return hit
        if dispatch == "bare" and meth not in _BARE_DISPATCH_BLOCKLIST:
            return list(self.by_bare.get(meth, []))
        return []

    # ------------------------------------------------------ reachability
    def edges(self, g: str, dispatch: str = "strict") -> tuple[str, ...]:
        key = (g, dispatch)
        memo = self._edges_memo.get(key)
        if memo is not None:
            return memo
        entry = self.funcs.get(g)
        out: list[str] = []
        if entry is not None:
            seen: set[str] = set()
            for call in entry.calls:
                for callee in self.resolve_call(g, call["n"], dispatch):
                    if callee not in seen:
                        seen.add(callee)
                        out.append(callee)
            # a function lexically encloses its nested defs: treat the
            # closure as part of the enclosing protocol (install() runs
            # inside swap_to's contract, feeders build their _steady body)
            for gq, _e in self._children_of(g):
                if gq not in seen:
                    seen.add(gq)
                    out.append(gq)
        res = tuple(out)
        self._edges_memo[key] = res
        return res

    def _children_of(self, g: str) -> list[tuple[str, FuncEntry]]:
        memo = getattr(self, "_children_memo", None)
        if memo is None:
            memo = {}
            for gq, e in self.funcs.items():
                if e.parent is not None:
                    rel = self.func_module[gq]
                    pg = f"{rel}::{e.parent}"
                    memo.setdefault(pg, []).append((gq, e))
            self._children_memo = memo
        return memo.get(g, [])

    def reachable(
        self, seeds: Iterable[str], dispatch: str = "strict"
    ) -> frozenset[str]:
        key = (frozenset(seeds), dispatch)
        memo = self._reach_memo.get(key)
        if memo is not None:
            return memo
        seen: set[str] = set()
        stack = [s for s in key[0] if s in self.funcs]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.edges(cur, dispatch))
        out = frozenset(seen)
        self._reach_memo[key] = out
        return out

    def reaches(
        self,
        start: str,
        pred: Callable[[FuncEntry], bool],
        dispatch: str = "strict",
        include_enclosing: bool = False,
    ) -> bool:
        """From `start`, is a function whose entry satisfies `pred`
        reachable (including `start` itself)? With `include_enclosing`,
        the lexical parent chain joins the seed set — a nested def runs
        inside its enclosing function's protocol, so evidence there
        counts for the closure."""
        seeds = [start]
        if include_enclosing:
            g = start
            while True:
                e = self.funcs.get(g)
                if e is None or e.parent is None:
                    break
                g = f"{self.func_module[g]}::{e.parent}"
                seeds.append(g)
        for g in self.reachable(seeds, dispatch):
            e = self.funcs.get(g)
            if e is not None and pred(e):
                return True
        return False

    # ----------------------------------------------------------- helpers
    def functions_in(self, rel: str) -> list[str]:
        idx = self.modules.get(rel)
        if idx is None:
            return []
        return [f"{rel}::{q}" for q in idx.functions]

    def str_tuple(self, rel_suffix: str, name: str) -> list[str] | None:
        """A module-level string-tuple constant, looked up by module path
        suffix (so the table survives repo-root-relative vs absolute
        naming differences)."""
        for rel, idx in self.modules.items():
            if rel.endswith(rel_suffix) and name in idx.str_tuples:
                return idx.str_tuples[name]
        return None


def iter_file_funcs(
    tree: ast.Module,
) -> Iterator[tuple[str, ast.FunctionDef | ast.AsyncFunctionDef, str | None]]:
    """(local qual, def node, owning class) for every function in `tree`,
    using EXACTLY the indexer's qual-generation scheme so AST nodes in a
    live FileContext line up with FuncEntry records from a cached index."""

    def walk(
        node: ast.AST, cls_name: str | None, parent: str | None
    ) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (
                    child.name if cls_name is None
                    else f"{cls_name}.{child.name}"
                )
                if parent is not None:
                    qual = f"{parent}.<locals>.{child.name}"
                yield qual, child, cls_name
                yield from walk(child, cls_name, qual)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child.name, None)
            else:
                yield from walk(child, cls_name, parent)

    yield from walk(tree, None, None)
