"""Rule registry. A selector is a rule id (``lock-across-await``) or a
family name (``concurrency``, ``determinism``, ``jax``, ``protocol``,
``sharding``, ``py310``)."""

from __future__ import annotations

from tools.graftlint.core import LintRule, RuleViolationError
from tools.graftlint.rules.concurrency import CONCURRENCY_RULES
from tools.graftlint.rules.determinism import DETERMINISM_RULES
from tools.graftlint.rules.durability import DURABILITY_RULES
from tools.graftlint.rules.jaxpurity import JAX_RULES
from tools.graftlint.rules.protocol import PROTOCOL_RULES
from tools.graftlint.rules.py310 import PY310_RULES
from tools.graftlint.rules.resilience import RESILIENCE_RULES
from tools.graftlint.rules.sharding import SHARDING_RULES

RULES: list[LintRule] = [
    *CONCURRENCY_RULES, *DETERMINISM_RULES, *DURABILITY_RULES, *JAX_RULES,
    *PROTOCOL_RULES, *PY310_RULES, *RESILIENCE_RULES, *SHARDING_RULES,
]


def rules_by_selector(selectors: list[str] | None) -> list[LintRule]:
    if not selectors:
        return list(RULES)
    known_ids = {r.id for r in RULES}
    known_families = {r.family for r in RULES}
    bad = [s for s in selectors if s not in known_ids | known_families]
    if bad:
        raise RuleViolationError(
            f"unknown rule selector(s) {bad}; known rules: "
            f"{sorted(known_ids)}, families: {sorted(known_families)}"
        )
    return [r for r in RULES if r.id in selectors or r.family in selectors]
