"""Concurrency rule family: thread/asyncio discipline.

The scheduler mixes one asyncio control loop with thread-world producers
(engine worker, replica server pool, metrics server, samplers) that meet
at ~18 lock sites. Every rule here encodes a discipline the codebase
already follows by convention; the rules make the conventions
unlandable to break:

- a THREADING lock may be held inside a coroutine only for a straight-
  line critical section — never across an ``await`` (the event loop runs
  other tasks while the lock is held; any of them touching the same lock
  deadlocks the loop);
- coroutines must not make blocking calls (``time.sleep``, requests,
  subprocess, socket/file I/O) — one blocked coroutine stalls every
  in-flight decision on the loop;
- attributes guarded by ``with self._lock`` in one method are guarded
  everywhere (a single unguarded write is the PhaseRecorder-snapshot
  race class all over again);
- ``asyncio.get_event_loop`` is banned: on a non-loop thread it creates
  a NEW loop silently (the bug class `FakeCluster._deliver` dances
  around); inside a coroutine ``get_running_loop`` is the correct spelling.

Lock-ish detection is by name: the final path segment matching
``lock|mutex|cond|condition`` (``self._lock``, ``send_lock``,
``_ID_LOCK``, ``self._inf_lock``). Name-based is deliberate — the
codebase's locks all follow it, and it needs no type inference.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    body_walk,
    dotted_name,
)

_LOCKISH = re.compile(r"(^|_)(lock|mutex|cond|condition|rlock)$", re.IGNORECASE)


def lockish_name(node: ast.AST) -> str | None:
    """The dotted name of a lock-looking expression, else None."""
    name = dotted_name(node)
    if name and _LOCKISH.search(name.rsplit(".", 1)[-1]):
        return name
    return None


def _async_funcs(ctx: FileContext) -> Iterator[ast.AsyncFunctionDef]:
    for func, _cls in ctx.functions():
        if isinstance(func, ast.AsyncFunctionDef):
            yield func


def _awaits_in(node: ast.AST) -> Iterator[ast.AST]:
    """Suspension points under `node`, not descending into nested defs.
    `yield` counts: inside an async def it makes an ASYNC GENERATOR, and
    each yield suspends to the consumer — the loop runs arbitrary code
    while the with-block's lock stays held (cluster/*.watch_pending_pods
    is exactly this shape, and keeps its yields outside the lock)."""
    for child in body_walk(node):
        if isinstance(child, (ast.Await, ast.AsyncFor, ast.AsyncWith, ast.Yield)):
            yield child


class LockAcrossAwait(LintRule):
    id = "lock-across-await"
    family = "concurrency"
    description = (
        "a threading lock (plain `with <lock>:`) held across an await — "
        "the event loop runs arbitrary other tasks while the lock is held"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for func in _async_funcs(ctx):
            for node in body_walk(func):
                # plain `with` only: `async with` takes asyncio primitives,
                # which are designed to be held across suspension points
                if not isinstance(node, ast.With):
                    continue
                held = [
                    lockish_name(item.context_expr)
                    for item in node.items
                    if lockish_name(item.context_expr)
                ]
                if not held:
                    continue
                for sus in _awaits_in(node):
                    yield ctx.finding(
                        self, sus,
                        f"`{held[0]}` is held across this suspension point "
                        f"(with-block opened at line {node.lineno}); release "
                        f"the lock before awaiting or use asyncio.Lock",
                    )


# Fully-qualified call prefixes that block the calling thread. The value
# is the hint shown to the author. Statically resolvable names only:
# method calls on socket/file OBJECTS (`sock.recv`, `f.read`) can't be
# typed without inference, so the entry points that create them (`open`,
# `socket.create_connection`, `urllib.request.urlopen`) are the guard.
_BLOCKING_CALLS: dict[str, str] = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "requests": "use a thread via asyncio.to_thread, or an async client",
    "subprocess": "use `await asyncio.create_subprocess_exec(...)`",
    "socket.create_connection": "use `await asyncio.open_connection(...)`",
    "urllib.request.urlopen": "run it in a thread via asyncio.to_thread",
    "os.system": "use `await asyncio.create_subprocess_shell(...)`",
    "open": "do file I/O via `await asyncio.to_thread(...)`",
}


class BlockingCallInAsync(LintRule):
    id = "blocking-call-in-async"
    family = "concurrency"
    description = (
        "a blocking call (time.sleep, requests.*, subprocess.*, "
        "socket.create_connection, urllib urlopen, os.system, open()) "
        "inside `async def` stalls the whole event loop"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for func in _async_funcs(ctx):
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if not name:
                    continue
                for prefix, hint in _BLOCKING_CALLS.items():
                    if name == prefix or name.startswith(prefix + "."):
                        yield ctx.finding(
                            self, node,
                            f"blocking call `{name}(...)` inside async def "
                            f"`{func.name}` — {hint}",
                        )
                        break


class SyncLockAcquireInAsync(LintRule):
    id = "lock-acquire-in-async"
    family = "concurrency"
    description = (
        "threading.Lock.acquire() called in a coroutine — the default "
        "blocking acquire parks the event loop thread"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for func in _async_funcs(ctx):
            for node in body_walk(func):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    continue
                lock = lockish_name(node.func.value)
                if lock is None:
                    continue
                if self._nonblocking(node):
                    continue
                yield ctx.finding(
                    self, node,
                    f"blocking `{lock}.acquire()` inside async def "
                    f"`{func.name}` parks the event loop thread; use a "
                    f"short `with {lock}:` critical section (no awaits) "
                    f"or an asyncio.Lock",
                )

    @staticmethod
    def _nonblocking(call: ast.Call) -> bool:
        """acquire(False) / acquire(blocking=False) / acquire(timeout=0)
        can't park the loop indefinitely."""
        for arg in call.args[:1]:
            if isinstance(arg, ast.Constant) and arg.value is False:
                return True
        for kw in call.keywords:
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
            if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value == 0:
                return True
        return False


class UnguardedAttrWrite(LintRule):
    id = "unguarded-attr-write"
    family = "concurrency"
    description = (
        "an attribute written under `with self.<lock>` in one method of a "
        "class but written WITHOUT the lock elsewhere in the same class"
    )

    # Methods that run before/after any concurrent access exists.
    _EXEMPT = {"__init__", "__new__", "__post_init__"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for cls in [n for n in ctx.all_nodes() if isinstance(n, ast.ClassDef)]:
            yield from self._check_class(ctx, cls)

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> Iterator[Finding]:
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        guarded: dict[str, str] = {}   # attr -> lock name that guards it
        writes: list[tuple[str, ast.AST, ast.FunctionDef | ast.AsyncFunctionDef, bool]] = []
        for m in methods:
            self_name = self._self_param(m)
            if self_name is None:
                continue
            for attr, node, under in self._attr_writes(m, self_name):
                if under is not None:
                    guarded.setdefault(attr, under)
                writes.append((attr, node, m, under is not None))
        for attr, node, m, under_lock in writes:
            if under_lock or attr not in guarded:
                continue
            if m.name in self._EXEMPT or m.name.endswith("_locked"):
                # __init__ predates concurrency; *_locked methods are the
                # repo's called-with-lock-held convention (cluster/kube.py)
                continue
            yield ctx.finding(
                self, node,
                f"`self.{attr}` is written under `with self.{guarded[attr]}` "
                f"elsewhere in class {cls.name} but unguarded here in "
                f"`{m.name}`",
            )

    @staticmethod
    def _self_param(m: ast.FunctionDef | ast.AsyncFunctionDef) -> str | None:
        args = m.args.posonlyargs + m.args.args
        return args[0].arg if args else None

    def _attr_writes(
        self, m: ast.AST, self_name: str
    ) -> Iterator[tuple[str, ast.AST, str | None]]:
        """(attr, node, guarding-lock-or-None) for every `self.x = ...` /
        `self.x += ...` / `self.x[k] = ...` in the method body."""

        def walk(node: ast.AST, lock: str | None) -> Iterator:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                inner = lock
                if isinstance(child, ast.With):
                    for item in child.items:
                        name = lockish_name(item.context_expr)
                        if name and name.startswith(self_name + "."):
                            inner = name.split(".", 1)[1]
                targets: list[ast.AST] = []
                if isinstance(child, ast.Assign):
                    targets = list(child.targets)
                elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                    targets = [child.target]
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        t = t.value
                    if isinstance(t, ast.Tuple):
                        for el in t.elts:
                            yield from _target(el, child, inner)
                        continue
                    yield from _target(t, child, inner)
                yield from walk(child, inner)

        def _target(t: ast.AST, stmt: ast.AST, lock: str | None) -> Iterator:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == self_name
                and lockish_name(t) is None  # assigning the lock itself is setup
            ):
                yield t.attr, stmt, lock

        yield from walk(m, None)


class EventLoopInThread(LintRule):
    id = "event-loop-in-thread"
    family = "concurrency"
    description = (
        "asyncio.get_event_loop() is banned: inside a coroutine use "
        "get_running_loop(); on a worker thread it silently creates a new, "
        "never-running loop"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.all_nodes():
            if (
                isinstance(node, ast.Call)
                and dotted_name(node.func) in (
                    "asyncio.get_event_loop", "get_event_loop",
                )
            ):
                yield ctx.finding(
                    self, node,
                    "asyncio.get_event_loop() — use asyncio.get_running_loop() "
                    "in async code, or pass the loop in explicitly for "
                    "thread-side call_soon_threadsafe handoffs",
                )


CONCURRENCY_RULES: list[LintRule] = [
    LockAcrossAwait(),
    BlockingCallInAsync(),
    SyncLockAcquireInAsync(),
    UnguardedAttrWrite(),
    EventLoopInThread(),
]
