"""Determinism rule family: byte-replayability of the trace plane.

The repo's replay story (sim/trace.canonical_bytes, the chaos harness's
canonical_chaos_bytes, the resident black-box, the decision journal) is
a BYTE contract: two runs with the same seed must serialize identical
artifacts, and the digests in rollout/registry.py make any divergence a
hard failure. Python offers four quiet ways to break that contract and
none of them is a runtime error:

- **unordered-set-in-canonical**: iterating a ``set`` yields
  hash-randomized order (PYTHONHASHSEED varies per process for str
  keys). If that order flows into a function that reaches a canonical
  writer, two identical runs serialize different bytes. Dicts are
  exempt on purpose — insertion order is a language guarantee since
  3.7, and the canonical writers sort keys anyway; it is specifically
  ``set`` iteration that has NO deterministic order.
- **unseeded-random**: ``random.*`` / ``np.random.*`` module-level
  functions use interpreter-global state no replay harness can pin
  per-component. Runtime modules must thread a ``random.Random(seed)``
  / ``np.random.default_rng(seed)`` instance (or a JAX PRNG key).
- **id-keyed-ordering**: ``id()`` is an address — it differs across
  runs by construction. Sorting by it, or keying a serialized mapping
  with it, bakes ASLR into the artifact.
- **wall-clock-in-replay**: a wall/monotonic clock read inside a
  function that reaches a canonical writer lands a nondeterministic
  value in a replay-compared payload. (The resilience family's
  raw-clock rule polices clock INJECTION discipline broadly; this rule
  is the narrow byte-contract version, scoped to writer-reaching
  functions only.)

"Reaches a canonical writer" rides the whole-repo graph: the writer
sink set is every call site flagged ``w`` at index time —
canonical_*_bytes, ``json.dump(s)`` with ``sort_keys=True`` (the repo's
canonical-JSON convention), and fed ``hashlib`` digest constructors.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    body_walk,
    dotted_name,
)
from tools.graftlint.rules.jaxpurity import _loop_scope


def _entry_writes_canonical(entry) -> bool:
    return any(c.get("w") for c in entry.calls)


def _writer_reaching_funcs(ctx: FileContext) -> list[tuple[str, ast.AST]]:
    """This file's functions from which the repo graph can reach a
    canonical-writer call site (the function's own body counts).
    Memoized per file: every rule in this family scopes on it."""
    cached = getattr(ctx, "_writer_reaching", None)
    if cached is not None:
        return cached
    repo = ctx.repo
    out: list[tuple[str, ast.AST]] = []
    for qual, node, _cls in ctx.graph_funcs():
        if repo.reaches(
            ctx.gqual(qual), _entry_writes_canonical, dispatch="strict"
        ):
            out.append((qual, node))
    ctx._writer_reaching = out
    return out


def _set_typed_names(func: ast.AST) -> set[str]:
    """Local names bound to set-valued expressions anywhere in `func`
    (linear approximation — good enough for the build-then-serialize
    shape these payload functions all have)."""
    names: set[str] = set()
    for node in body_walk(func):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value, names):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name) \
                and _is_set_expr(node.value, names):
            names.add(node.target.id)
    return names


def _is_set_expr(node: ast.AST, set_names: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name == "set" or name == "frozenset":
            return True
        # set-producing methods/operations on known sets
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "union", "intersection", "difference", "symmetric_difference",
        ):
            return _is_set_expr(node.func.value, set_names)
    if isinstance(node, ast.Name):
        return node.id in set_names
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    ):
        return _is_set_expr(node.left, set_names) or _is_set_expr(
            node.right, set_names
        )
    return False


# Consumers whose result does not depend on argument order: a
# comprehension/generator fed straight into one of these launders the
# set's hash-randomized order away, so its iteration is harmless.
_ORDER_FREE_CONSUMERS = frozenset({
    "sorted", "min", "max", "sum", "len", "any", "all", "set", "frozenset",
})


class UnorderedSetInCanonical(LintRule):
    id = "unordered-set-in-canonical"
    family = "determinism"
    description = (
        "iteration over a set (hash-randomized order) inside a function "
        "that reaches a canonical-JSON/trace/digest writer, without an "
        "intervening sorted() — two identical runs serialize different "
        "bytes"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        for qual, func in _writer_reaching_funcs(ctx):
            set_names = _set_typed_names(func)
            # `sorted(x for x in some_set)` is the FIX, not the bug: a
            # comprehension handed straight to an order-insensitive
            # consumer never leaks the set's order into the payload
            order_free: set[int] = set()
            for node in body_walk(func):
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func) in _ORDER_FREE_CONSUMERS:
                    for a in node.args:
                        if isinstance(a, (ast.ListComp, ast.SetComp,
                                          ast.GeneratorExp)):
                            order_free.add(id(a))
            for node in body_walk(func):
                iters: list[ast.AST] = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters = [node.iter]
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    if id(node) in order_free:
                        continue
                    iters = [gen.iter for gen in node.generators]
                for it in iters:
                    # `for x in sorted(s)` is the fix, not the bug: only
                    # the raw set expression itself is unordered
                    if _is_set_expr(it, set_names):
                        yield ctx.finding(
                            self, it,
                            f"iteration over a set in `{qual}`, which "
                            f"reaches a canonical writer — set order is "
                            f"hash-randomized per process, so the "
                            f"serialized bytes differ across identical "
                            f"runs; wrap the set in sorted(...) before "
                            f"iterating",
                        )


_RANDOM_GLOBAL_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "getrandbits", "seed",
})
_NP_RANDOM_OK = frozenset({"default_rng", "Generator", "SeedSequence"})


class UnseededRandom(LintRule):
    id = "unseeded-random"
    family = "determinism"
    description = (
        "random.* / np.random.* module-level (global-state) call in a "
        "replayable runtime module — thread a seeded Random/default_rng "
        "instance instead"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        for node in ctx.all_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            head, _, rest = name.partition(".")
            if head == "random" and rest in _RANDOM_GLOBAL_FNS:
                yield ctx.finding(
                    self, node,
                    f"`{name}(...)` uses the interpreter-global RNG — "
                    f"replay cannot pin its state per component; thread a "
                    f"`random.Random(seed)` instance (or derive from the "
                    f"run's seed) instead",
                )
            elif head in ("np", "numpy") and rest.startswith("random."):
                fn = rest.split(".", 1)[1]
                if fn not in _NP_RANDOM_OK:
                    yield ctx.finding(
                        self, node,
                        f"`{name}(...)` uses numpy's legacy global RNG — "
                        f"replay cannot pin its state per component; use "
                        f"`np.random.default_rng(seed)` and thread the "
                        f"generator",
                    )


class IdKeyedOrdering(LintRule):
    id = "id-keyed-ordering"
    family = "determinism"
    description = (
        "id()-derived ordering or mapping keys in a function that "
        "reaches a canonical writer — id() is an address, different "
        "every run"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        for qual, func in _writer_reaching_funcs(ctx):
            for node in body_walk(func):
                if isinstance(node, ast.Call):
                    for kw in node.keywords:
                        if kw.arg == "key" and self._mentions_id(kw.value):
                            yield ctx.finding(
                                self, kw.value,
                                f"sort key derived from id() in `{qual}`, "
                                f"which reaches a canonical writer — id() "
                                f"is a memory address, so the order (and "
                                f"the serialized bytes) changes every run; "
                                f"sort by a stable field instead",
                            )
                elif isinstance(node, ast.Dict):
                    for k in node.keys:
                        if k is not None and self._mentions_id(k):
                            yield ctx.finding(
                                self, k,
                                f"mapping keyed by id() in `{qual}`, which "
                                f"reaches a canonical writer — the keys "
                                f"are addresses and differ across runs; "
                                f"key by a stable identifier",
                            )
                elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, ast.Store
                ) and self._mentions_id(node.slice):
                    yield ctx.finding(
                        self, node.slice,
                        f"store keyed by id() in `{qual}`, which reaches "
                        f"a canonical writer — key by a stable identifier",
                    )

    @staticmethod
    def _mentions_id(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id == "id":
            return True  # key=id
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and dotted_name(sub.func) == "id":
                return True
        return False


_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})


class WallClockInReplay(LintRule):
    id = "wall-clock-in-replay"
    family = "determinism"
    description = (
        "a wall/monotonic clock read inside a function that reaches a "
        "canonical writer — a nondeterministic value in a "
        "replay-compared payload"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        for qual, func in _writer_reaching_funcs(ctx):
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name in _CLOCK_CALLS:
                    yield ctx.finding(
                        self, node,
                        f"`{name}()` inside `{qual}`, which reaches a "
                        f"canonical writer — a raw clock value in a "
                        f"replay-compared payload breaks the byte "
                        f"contract; use the injected clock (the kvplane/"
                        f"chaos pattern) or keep timestamps out of the "
                        f"canonical payload",
                    )


DETERMINISM_RULES: list[LintRule] = [
    UnorderedSetInCanonical(),
    UnseededRandom(),
    IdKeyedOrdering(),
    WallClockInReplay(),
]
