"""durability rule family — state writes that a crash can tear.

Grown alongside the durable decision journal (sched/journal.py): every
rule here encodes a discipline the crash-restart chaos regimes prove at
runtime, caught statically instead. The reference shape for both rules
is rollout/registry.py — write aside, flush, ``os.fsync``, one
``os.replace``.

- **nonatomic-state-write**: an ``open(path, "w"/"wb")`` in a runtime
  module whose enclosing function never calls
  ``os.replace``/``os.rename``. Writing a state file in place means a
  crash mid-write leaves a TORN file under the live name — the next
  process reads half a JSON document and dies on parse, which is a
  worse failure than losing the update entirely. The sanctioned shape
  writes to a side name and publishes with one atomic rename; a
  function containing the rename is taken to be that shape (the write
  it contains is the write-aside half).
- **rename-without-fsync**: an ``os.rename``/``os.replace``/
  ``Path.rename`` call in a runtime-module function that never calls
  ``os.fsync``/``os.fdatasync``. Rename alone orders METADATA, not
  data: a crash after the rename but before writeback can leave a torn
  tree under the final name (the exact window models/loader.py's
  checkpoint swap carried until the durability round). Renames of
  throwaway paths exist — suppress with a justified pragma.

Scope: runtime modules (``k8s_llm_scheduler_tpu/``) plus the fixture
corpus. Tests and tools write scratch files whose loss is free; bench
output files are operator artifacts.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    body_walk,
    dotted_name,
)

_WRITE_MODES = ("w", "wb", "w+", "wb+", "wt")
_RENAME_NAMES = ("os.rename", "os.replace")
_FSYNC_NAMES = ("os.fsync", "os.fdatasync")


def _in_scope(name: str) -> bool:
    if name.startswith("k8s_llm_scheduler_tpu/"):
        return True
    # the fixture corpus stays in scope so the detectors stay testable
    return "fixtures/graftlint" in name


def _call_mode(node: ast.Call) -> str | None:
    """The literal mode string of an open() call, else None."""
    fn = dotted_name(node.func)
    if fn not in ("open", "io.open"):
        return None
    mode_arg = None
    if len(node.args) >= 2:
        mode_arg = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode_arg = kw.value
    if isinstance(mode_arg, ast.Constant) and isinstance(mode_arg.value, str):
        return mode_arg.value
    return None


def _func_calls(func: ast.AST) -> list[ast.Call]:
    return [n for n in body_walk(func) if isinstance(n, ast.Call)]


def _has_call(calls: list[ast.Call], names: tuple[str, ...]) -> bool:
    return any(dotted_name(c.func) in names for c in calls)


class _NonAtomicStateWrite(LintRule):
    id = "nonatomic-state-write"
    family = "durability"
    description = (
        "open(path, 'w') in a runtime module with no os.replace/os.rename "
        "in the same function — a crash mid-write tears the live file; "
        "write aside and publish with one atomic rename"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _in_scope(ctx.name):
            return
        for func, _cls in ctx.functions():
            calls = _func_calls(func)
            if _has_call(calls, _RENAME_NAMES):
                continue  # the write-aside half of an atomic publish
            if any(
                isinstance(c.func, ast.Attribute)
                and c.func.attr in ("rename", "replace")
                for c in calls
            ):
                continue  # Path.rename/replace counts as the publish too
            for call in calls:
                mode = _call_mode(call)
                if mode in _WRITE_MODES:
                    yield ctx.finding(
                        self, call,
                        "non-atomic state write: open(..., "
                        f"{mode!r}) with no atomic rename in "
                        "this function — a crash mid-write leaves a torn "
                        "file under the live name (write aside + "
                        "os.replace; see rollout/registry.py)",
                    )


class _RenameWithoutFsync(LintRule):
    id = "rename-without-fsync"
    family = "durability"
    description = (
        "os.rename/os.replace/Path.rename in a runtime-module function "
        "with no os.fsync — rename orders metadata, not data; a crash "
        "after the rename can leave a torn tree under the final name"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _in_scope(ctx.name):
            return
        for func, _cls in ctx.functions():
            calls = _func_calls(func)
            if _has_call(calls, _FSYNC_NAMES):
                continue
            # a function that delegates to a tree-fsync helper is the
            # sanctioned shape too (models/loader._fsync_tree)
            if any("fsync" in dotted_name(c.func) for c in calls):
                continue
            for call in calls:
                fn = dotted_name(call.func)
                # os.rename/os.replace by name; Path.rename by shape
                # (attribute call named `rename`, exactly one positional
                # target). Attribute `.replace` is deliberately NOT
                # shape-matched: dataclasses.replace/str.replace share
                # the name, and os.replace is already caught above.
                is_rename = fn in _RENAME_NAMES or (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "rename"
                    and len(call.args) == 1
                    and not call.keywords
                )
                if is_rename:
                    yield ctx.finding(
                        self, call,
                        "rename without fsync: the renamed data may not "
                        "be on disk when the name changes — fsync the "
                        "content first (rollout/registry.py discipline)",
                    )


DURABILITY_RULES: list[LintRule] = [
    _NonAtomicStateWrite(),
    _RenameWithoutFsync(),
]
