"""JAX purity rule family: tracer/host-sync discipline in jit'd code.

Everything on the decision hot path lives inside `jax.jit` (CONTRIBUTING
ground rule); these rules keep the jit boundary honest:

- **host syncs** (`.item()`, `.tolist()`, `np.asarray`, `jax.device_get`,
  `float()/int()` on array-shaped expressions) inside any function
  REACHABLE from a `@jax.jit` / `jax.jit(fn)` / `shard_map` root are a
  trace-time error at best, a silent per-call device round trip at worst;
- **Python-side mutation** of closed-over / self state inside traced code
  runs once at trace time and never again — the classic "my counter
  stopped at 1" bug;
- **static_argnums** positions must receive hashable values (a list/dict
  literal at a static position raises at every call; a mutable default
  on a static parameter raises on the first defaulted call);
- a buffer passed at a **donate_argnums** position is dead after the
  call — reusing it reads deallocated (or aliased-output) memory.

Reachability rides the whole-repo interprocedural graph (``ctx.repo``,
tools/graftlint/repograph.py) under STRICT dispatch: jit roots are
decorated defs plus every def whose bare name any module wraps in
`jax.jit(...)`/`shard_map(...)` — the module that DEFINES a jitted
function is usually not the one that jits it (engine/engine.py jits
models/llama.py's forwards), and with one graph the llama helpers are
analyzed no matter which file asked. Strict dispatch never guesses an
unannotated receiver, which keeps "reachable from a jit root"
false-positive-poor. The sharding-specific rules that used to live here
moved to the ``sharding`` family (rules/sharding.py) when they went
interprocedural.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    body_walk,
    dotted_name,
)

_JIT_WRAPPERS = ("jax.jit", "jit", "pjit", "jax.pjit")
_SHMAP_WRAPPERS = (
    "shard_map", "jax.shard_map", "shard_map_compat",
    "jax.experimental.shard_map.shard_map",
)


def _is_jit_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name in _JIT_WRAPPERS or name in _SHMAP_WRAPPERS


def _wrapped_bare_name_of(node: ast.AST) -> str:
    """The bare function name a jit/shard_map call wraps, seeing through
    `functools.partial(fn, ...)` (the engine's idiom for binding closure
    constants: `jax.jit(functools.partial(_wave_impl, ...))`)."""
    if isinstance(node, ast.Call) and dotted_name(node.func) in (
        "partial", "functools.partial",
    ) and node.args:
        node = node.args[0]
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


def _is_jit_decorator(dec: ast.AST) -> bool:
    """@jax.jit, @jit, @partial(jax.jit, ...), @functools.partial(jax.jit)."""
    if dotted_name(dec) in _JIT_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        name = dotted_name(dec.func)
        if name in _JIT_WRAPPERS or name in _SHMAP_WRAPPERS:
            return True
        if name in ("partial", "functools.partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_WRAPPERS + _SHMAP_WRAPPERS
    return False


def jit_reachable_here(ctx: FileContext) -> list[tuple[str, ast.AST]]:
    """This file's functions (local qual + def node) that the whole-repo
    graph says are reachable from a jit/shard_map root, memoized per
    file. The graph walks callees across modules; the AST walk for the
    actual hazard classification stays local to this file."""
    cached = getattr(ctx, "_jit_reachable_here", None)
    if cached is not None:
        return cached
    repo = ctx.repo
    roots = repo.jit_roots()
    out: list[tuple[str, ast.AST]] = []
    if roots:
        reach = repo.reachable(roots, dispatch="strict")
        for qual, node, _cls in ctx.graph_funcs():
            if ctx.gqual(qual) in reach:
                out.append((qual, node))
    ctx._jit_reachable_here = out
    return out


_HOST_SYNC_METHODS = ("item", "tolist", "numpy", "block_until_ready")
_HOST_SYNC_CALLS = (
    "jax.device_get", "device_get", "np.asarray", "numpy.asarray",
    "np.array", "numpy.array",
)


class HostSyncInJit(LintRule):
    id = "jit-host-sync"
    family = "jax"
    description = (
        "host synchronization (.item(), np.asarray, jax.device_get, "
        "float()/int() on arrays) inside a function reachable from a "
        "jax.jit/shard_map root"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for qual, func in jit_reachable_here(ctx):
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node)
                if msg:
                    yield ctx.finding(
                        self, node,
                        f"{msg} inside `{qual}`, which is reachable from a "
                        f"jit/shard_map root — a trace-time error or a "
                        f"silent per-call device round trip; move host "
                        f"conversion outside the traced function",
                    )

    @staticmethod
    def _classify(call: ast.Call) -> str | None:
        if isinstance(call.func, ast.Attribute) and call.func.attr in _HOST_SYNC_METHODS:
            return f"host sync `.{call.func.attr}()`"
        name = dotted_name(call.func)
        if name in _HOST_SYNC_CALLS:
            return f"host sync `{name}(...)`"
        if name in ("float", "int", "bool") and call.args:
            arg = call.args[0]
            # Heuristic: only array-shaped expressions (attribute chains,
            # subscripts) — bare names and literals are usually Python
            # scalars / static args and would drown the signal.
            if isinstance(arg, (ast.Attribute, ast.Subscript)):
                return f"host sync `{name}()` on `{ast.unparse(arg)}`"
        return None


_MUTATORS = (
    "append", "extend", "add", "update", "pop", "remove", "insert",
    "setdefault", "clear", "popitem", "discard",
)


class ClosureMutationInJit(LintRule):
    id = "jit-closure-mutation"
    family = "jax"
    description = (
        "Python-level mutation of closed-over/self state inside traced "
        "code — it runs once at trace time, then never again"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for qual, func in jit_reachable_here(ctx):
            local = self._local_names(func)
            for node in body_walk(func):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                    yield ctx.finding(
                        self, node,
                        f"`{kind} {', '.join(node.names)}` inside traced "
                        f"`{qual}` — the rebind happens at trace time only",
                    )
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in ("self", "cls")
                        ):
                            yield ctx.finding(
                                self, t,
                                f"write to `{ast.unparse(t)}` inside traced "
                                f"`{qual}` happens at trace time only (and "
                                f"leaks a tracer into object state)",
                            )
                elif isinstance(node, ast.Expr):
                    # Only DISCARDED results: `updates = optimizer.update(...)`
                    # is the pure optax idiom, `seen.append(x)` is the bug.
                    call = node.value
                    if (
                        isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr in _MUTATORS
                        and isinstance(call.func.value, ast.Name)
                        and call.func.value.id not in local
                    ):
                        yield ctx.finding(
                            self, call,
                            f"`.{call.func.attr}()` on closed-over "
                            f"`{call.func.value.id}` inside traced `{qual}` "
                            f"mutates host state at trace time only",
                        )

    @staticmethod
    def _local_names(func: ast.AST) -> set[str]:
        a = func.args
        names = {
            arg.arg
            for arg in a.posonlyargs + a.args + a.kwonlyargs
            + ([a.vararg] if a.vararg else []) + ([a.kwarg] if a.kwarg else [])
        }
        for node in body_walk(func):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For, ast.AsyncFor)):
                t = node.target
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            elif isinstance(node, (ast.withitem,)) and node.optional_vars is not None:
                for n in ast.walk(node.optional_vars):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            elif isinstance(node, ast.comprehension):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        return names


def _kw_const_list(keywords: list[ast.keyword], kw_name: str, typ: type) -> list:
    """Constant values of type `typ` in keyword `kw_name` (scalar or
    tuple/list literal); [] when absent or not statically resolvable."""
    for kw in keywords:
        if kw.arg != kw_name:
            continue
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            return [
                el.value for el in kw.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, typ)
            ]
        if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, typ):
            return [kw.value.value]
    return []


def _jit_wrap_info(
    call: ast.Call,
) -> tuple[str, list[int], list[str], list[int], int] | None:
    """(wrapped bare name, static_argnums, static_argnames,
    donate_argnums, positional offset) for a `jax.jit(fn, ...)` call;
    None for anything else.

    Sees through `functools.partial(fn, ...)` like the root collector
    does; `offset` is the number of POSITIONAL args the partial binds —
    static/donate positions refer to the partial's (shifted) signature,
    so checks against the underlying def must add it. The engine's idiom
    binds closure constants by KEYWORD (offset 0)."""
    if dotted_name(call.func) not in _JIT_WRAPPERS or not call.args:
        return None
    wrapped = call.args[0]
    offset = 0
    if isinstance(wrapped, ast.Call) and dotted_name(wrapped.func) in (
        "partial", "functools.partial",
    ) and wrapped.args:
        offset = len(wrapped.args) - 1
        wrapped = wrapped.args[0]
    bare = dotted_name(wrapped)
    bare = bare.rsplit(".", 1)[-1] if bare else ""
    return (
        bare,
        _kw_const_list(call.keywords, "static_argnums", int),
        _kw_const_list(call.keywords, "static_argnames", str),
        _kw_const_list(call.keywords, "donate_argnums", int),
        offset,
    )


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


class NonHashableStatic(LintRule):
    id = "jit-static-hashable"
    family = "jax"
    description = (
        "a static_argnums/static_argnames position receiving an unhashable "
        "value (list/dict/set literal, or a mutable default) — TypeError "
        "at every call"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        # this file's defs by bare name (the wrapped def and its jit wrap
        # normally share a module; cross-module default-checking is the
        # repo graph's job and not worth the noise here)
        by_bare: dict[str, list[ast.AST]] = {}
        for func, _cls in ctx.functions():
            by_bare.setdefault(func.name, []).append(func)
        # jitted-name -> (static positions, static names); covers
        # `name = jax.jit(fn, static_argnums=...)` and
        # `self._x = jax.jit(fn, ...)` assignments.
        jitted: dict[str, tuple[list[int], list[str]]] = {}
        for node in ctx.all_nodes():
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            info = _jit_wrap_info(node.value)
            if info is None:
                continue
            bare, nums, names, _don, offset = info
            for t in node.targets:
                tn = dotted_name(t)
                if tn and (nums or names):
                    jitted[tn] = (nums, names)
            # mutable default on a static parameter of the wrapped fn
            for func in by_bare.get(bare, []):
                yield from self._check_func_defaults(ctx, func, nums, names, offset)
        # decorated functions: defaults + direct call sites by name
        for func, cls in ctx.functions():
            for dec in getattr(func, "decorator_list", []):
                if isinstance(dec, ast.Call) and _is_jit_decorator(dec):
                    nums = _kw_const_list(dec.keywords, "static_argnums", int)
                    names = _kw_const_list(dec.keywords, "static_argnames", str)
                    if nums or names:
                        jitted.setdefault(func.name, (nums, names))
                        yield from self._check_func_defaults(ctx, func, nums, names)
        # call sites
        for node in ctx.all_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in jitted:
                continue
            nums, names = jitted[name]
            for pos in nums:
                if pos < len(node.args) and isinstance(node.args[pos], _UNHASHABLE):
                    yield ctx.finding(
                        self, node.args[pos],
                        f"unhashable literal at static_argnums position {pos} "
                        f"of jitted `{name}` — static args are dict keys of "
                        f"the compile cache; pass a tuple or a scalar",
                    )
            for kw in node.keywords:
                if kw.arg in names and isinstance(kw.value, _UNHASHABLE):
                    yield ctx.finding(
                        self, kw.value,
                        f"unhashable literal for static_argnames "
                        f"`{kw.arg}` of jitted `{name}` — pass a tuple or a "
                        f"scalar",
                    )

    def _check_func_defaults(
        self, ctx, func, nums, names, offset=0
    ) -> Iterator[Finding]:
        a = func.args
        params = a.posonlyargs + a.args
        defaults = [None] * (len(params) - len(a.defaults)) + list(a.defaults)
        for pos, (param, default) in enumerate(zip(params, defaults)):
            # static positions are in the (possibly partial-shifted)
            # wrapped signature; underlying param `pos` sits at
            # wrapped position `pos - offset`
            static = (pos - offset) in nums or param.arg in names
            if static and isinstance(default, _UNHASHABLE):
                yield ctx.finding(
                    self, default,
                    f"static parameter `{param.arg}` of `{func.name}` has an "
                    f"unhashable default — the first defaulted call raises "
                    f"TypeError",
                )


_LOOP_SYNC_METHODS = ("block_until_ready", "item")
_LOOP_SYNC_CALLS = (
    "np.asarray", "numpy.asarray", "jax.device_get", "device_get",
)


def _loop_scope(name: str) -> bool:
    """Runtime modules only (same discipline as the resilience family's
    raw-clock rule): the engine/sched hot paths are where a per-iteration
    sync costs a dispatch-pipeline stall; tests, tools, and bench.py sync
    deliberately. The fixture corpus stays in scope so the detector stays
    testable."""
    if name.startswith("k8s_llm_scheduler_tpu/"):
        return True
    return "fixtures/graftlint" in name


class DeviceSyncInLoop(LintRule):
    id = "device-sync-in-loop"
    family = "jax"
    description = (
        "host-device synchronization (.block_until_ready()/.item()/"
        "np.asarray/jax.device_get) inside a for/while body in a runtime "
        "module — per-iteration syncs serialize the dispatch pipeline"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        seen: set[int] = set()  # nested loops must not double-report
        for node in ctx.all_nodes():
            if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                continue
            # Only the BODY repeats: the loop's iterable/test expressions
            # run once (or once per re-check, host-side), and an `else:`
            # clause executes exactly once after the loop — neither is a
            # per-iteration sync.
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call) or id(sub) in seen:
                        continue
                    msg = self._classify(sub)
                    if msg:
                        seen.add(id(sub))
                        yield ctx.finding(
                            self, sub,
                            f"{msg} inside a loop body — one host round "
                            f"trip PER ITERATION is the synchronization "
                            f"boundary the fused decode runtime exists to "
                            f"remove (Kernel Looping); hoist the sync out "
                            f"of the loop, batch it into one device_get, "
                            f"or justify via pragma",
                        )

    @staticmethod
    def _classify(call: ast.Call) -> str | None:
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _LOOP_SYNC_METHODS:
            return f"device sync `.{call.func.attr}()`"
        name = dotted_name(call.func)
        if name in _LOOP_SYNC_CALLS:
            return f"device sync `{name}(...)`"
        return None


class DonatedBufferReuse(LintRule):
    id = "jit-donated-reuse"
    family = "jax"
    description = (
        "a variable passed at a donate_argnums position is read again "
        "after the call — the buffer was donated and may alias the output"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        donating: dict[str, list[int]] = {}
        for node in ctx.all_nodes():
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = _jit_wrap_info(node.value)
                if info and info[3]:
                    for t in node.targets:
                        tn = dotted_name(t)
                        if tn:
                            donating[tn] = info[3]
        for func, _cls in ctx.functions():
            for dec in getattr(func, "decorator_list", []):
                if isinstance(dec, ast.Call) and _is_jit_decorator(dec):
                    don = _kw_const_list(dec.keywords, "donate_argnums", int)
                    if don:
                        donating.setdefault(func.name, don)
        if not donating:
            return
        for func, _cls in ctx.functions():
            yield from self._check_body(ctx, func, donating)

    def _check_body(
        self, ctx: FileContext, func: ast.AST, donating: dict[str, list[int]]
    ) -> Iterator[Finding]:
        # linear pass: donated bare-name args are dead from the call's line
        # until reassigned
        dead: dict[str, int] = {}  # name -> line it was donated at
        for node in body_walk(func):
            if isinstance(node, ast.Call):
                positions = donating.get(dotted_name(node.func))
                if positions:
                    for pos in positions:
                        if pos < len(node.args):
                            name = node.args[pos]
                            if isinstance(name, ast.Name):
                                dead[name.id] = node.lineno
        if not dead:
            return
        assigns: dict[str, list[int]] = {}
        for node in body_walk(func):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id in dead:
                            assigns.setdefault(n.id, []).append(node.lineno)
        for node in body_walk(func):
            if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
                continue
            donated_at = dead.get(node.id)
            if donated_at is None or node.lineno <= donated_at:
                continue
            # a reassignment at/after the donation revives the name (the
            # idiomatic `pages = _append(pages, ...)` rebinds on the
            # donation line itself)
            if any(donated_at <= a <= node.lineno for a in assigns.get(node.id, [])):
                continue
            yield ctx.finding(
                self, node,
                f"`{node.id}` was donated at line {donated_at} "
                f"(donate_argnums) and is read again here — the buffer is "
                f"deallocated or aliased by the output; use the returned "
                f"value instead",
            )


class DispatchInPersistentPath(LintRule):
    id = "dispatch-in-persistent-path"
    family = "jax"
    description = (
        "an XLA dispatch (jax.*/jnp.* call, a jitted program, or "
        ".block_until_ready) inside the persistent loop's steady-state "
        "path — the path whose whole contract is zero per-decision "
        "dispatches"
    )

    # The persistent serving plane's ZERO-DISPATCH steady-state contract
    # (engine/persistent/): once the resident loop is launched, every
    # per-decision interaction is ring traffic — numpy in, numpy out. A
    # function is a declared steady-path function when its name ends in
    # `_steady` (the feeder/harvester naming convention server.py
    # established) or is one of the ordered-io_callback bodies; anything
    # the repo graph says is reachable from one (strict dispatch, across
    # modules now) is on the steady path too.

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        repo = ctx.repo
        steady = repo.steady_roots()
        if not steady:
            return
        reach = repo.reachable(steady, dispatch="strict")
        on_path = [
            (qual, node)
            for qual, node, _cls in ctx.graph_funcs()
            if ctx.gqual(qual) in reach
        ]
        if not on_path:
            return
        # `name = jax.jit(...)` assignment targets anywhere in the module
        # (`self._jitted = jax.jit(...)`): calling one re-enters the
        # dispatch path even though the name itself is not jax.*
        jitted_names: set[str] = set()
        for node in ctx.all_nodes():
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and _is_jit_call(node.value):
                for t in node.targets:
                    tn = dotted_name(t)
                    if tn:
                        jitted_names.add(tn)
        jit_roots = repo.jit_roots()
        for qual, func in on_path:
            g = ctx.gqual(qual)
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node, repo, g, jitted_names, jit_roots)
                if msg:
                    yield ctx.finding(
                        self, node,
                        f"{msg} inside `{qual}`, which is on the "
                        f"persistent loop's steady-state path — steady "
                        f"serving must be pure ring traffic (numpy + "
                        f"threading), or the zero-dispatch-per-decision "
                        f"contract is silently broken; route device work "
                        f"through the launch/quiesce boundary or justify "
                        f"via pragma",
                    )

    @staticmethod
    def _classify(
        call: ast.Call, repo, caller_g: str, jitted_names: set[str],
        jit_roots: frozenset[str],
    ) -> str | None:
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "block_until_ready":
            return "device sync `.block_until_ready()`"
        name = dotted_name(call.func)
        if not name:
            return None
        if name in jitted_names:
            return f"call to jitted program `{name}`"
        head = name.split(".", 1)[0]
        if head in ("jax", "jnp"):
            return f"XLA dispatch `{name}(...)`"
        # a strictly-resolved callee that is itself a jit root re-enters
        # the dispatch path by name
        for callee in repo.resolve_call(caller_g, name, dispatch="strict"):
            if callee in jit_roots:
                bare = name.rsplit(".", 1)[-1]
                return f"call to jit-rooted `{bare}`"
        return None


JAX_RULES: list[LintRule] = [
    HostSyncInJit(),
    ClosureMutationInJit(),
    NonHashableStatic(),
    DeviceSyncInLoop(),
    DonatedBufferReuse(),
    DispatchInPersistentPath(),
]
