"""Protocol rule family: typestate-style call-graph contracts.

Two of the repo's safety protocols are "if you do A you must also do B"
shapes that no per-file lint can see:

- **swap-without-epoch-bump**: swapping serving parameters
  (`swap_params` / `swap_engine_params`) invalidates every cached
  decision and every pinned prefix-KV snapshot. The coherence story
  (decision-cache generation, `prefix_epoch`, kvplane generation) only
  holds if every path that reaches a swap sink ALSO reaches bump
  evidence — a `bump_generation(...)` call or an augmented assignment
  to an epoch/generation counter. A swap path with no bump serves
  stale-model decisions from a warm cache: no crash, wrong answers.
- **bind-without-fence-check**: the lease-fencing protocol
  (fleet/lease.py, sched/journal.py) demands that a binder verify
  ownership (`check_fence`/`owns`) before the bind POST; a bind with
  no reachable fence check is exactly the zombie-scheduler double-bind
  the fences exist to prevent.

Both rules run under BARE dispatch deliberately — the generous linking
polarity is SAFE here, because reaching MORE functions can only find
more evidence and suppress a finding, never create one. (The jax
family's reachability runs strict for the same reason in reverse.)
Evidence search also seeds the lexical parent chain: a nested
`install()` closure runs inside `swap_to`'s contract, so a bump in the
enclosing function counts for the closure.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    body_walk,
    dotted_name,
)
from tools.graftlint.rules.jaxpurity import _loop_scope

_SWAP_SINKS = frozenset({"swap_params", "swap_engine_params"})
_BUMP_CALLS = frozenset({"bump_generation"})
_BUMP_ATTRS = frozenset({"prefix_epoch", "generation", "epoch", "_generation"})

_BIND_SINKS = frozenset({"bind_pod_to_node"})
_FENCE_CALLS = frozenset({
    "check_fence", "owns", "_owns", "_store_fence", "_verify",
})


def _entry_bumps(entry) -> bool:
    if any(a in _BUMP_ATTRS for a in entry.aug_attrs):
        return True
    return any(
        c["n"].rsplit(".", 1)[-1] in _BUMP_CALLS for c in entry.calls
    )


def _entry_fences(entry) -> bool:
    return any(
        c["n"].rsplit(".", 1)[-1] in _FENCE_CALLS for c in entry.calls
    )


class SwapWithoutEpochBump(LintRule):
    id = "swap-without-epoch-bump"
    family = "protocol"
    description = (
        "a path reaching a swap_params-class sink with no reachable "
        "generation/epoch bump — caches keep serving the old model's "
        "decisions"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        repo = ctx.repo
        for qual, func, _cls in ctx.graph_funcs():
            # the sink's own implementation is not a "path to the sink" —
            # `InferenceEngine.swap_params` bumping prefix_epoch inside
            # itself is the protocol working, not a caller to audit
            if qual.rsplit(".", 1)[-1] in _SWAP_SINKS:
                continue
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if not name or name.rsplit(".", 1)[-1] not in _SWAP_SINKS:
                    continue
                # bare dispatch + the lexical parent chain: evidence
                # anywhere the swap path can reach counts — including
                # the swap sink's own body (engine.swap_params bumps
                # prefix_epoch internally; callers of THAT are safe)
                if repo.reaches(
                    ctx.gqual(qual), _entry_bumps,
                    dispatch="bare", include_enclosing=True,
                ):
                    continue
                yield ctx.finding(
                    self, node,
                    f"`{name}(...)` in `{qual}` swaps serving params but "
                    f"no generation/epoch bump is reachable from this "
                    f"path (no bump_generation call, no "
                    f"prefix_epoch/generation += 1) — decision caches and "
                    f"pinned prefix KV keep serving the OLD model; bump "
                    f"every generation the swap invalidates, or justify "
                    f"via pragma",
                )


class BindWithoutFenceCheck(LintRule):
    id = "bind-without-fence-check"
    family = "protocol"
    description = (
        "a binder path reaching the bind POST with no reachable lease "
        "fence check — the zombie-scheduler double-bind the fences "
        "exist to prevent"
    )

    # The fencing protocol is a fleet/sched-plane contract; engine code
    # never binds pods. Fixtures stand in for binder modules.
    _SCOPES = (
        "k8s_llm_scheduler_tpu/fleet/",
        "k8s_llm_scheduler_tpu/sched/",
    )

    def _in_scope(self, name: str) -> bool:
        if any(name.startswith(s) for s in self._SCOPES):
            return True
        return "fixtures/graftlint" in name

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not self._in_scope(ctx.name):
            return
        repo = ctx.repo
        for qual, func, _cls in ctx.graph_funcs():
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if not name or name.rsplit(".", 1)[-1] not in _BIND_SINKS:
                    continue
                # bare dispatch: `self._binder.bind_pod_to_node(...)`
                # links to every bind_pod_to_node impl, including the
                # fenced wrapper whose body holds the check — an
                # UNfenced call chain finds no evidence anywhere
                if repo.reaches(
                    ctx.gqual(qual), _entry_fences,
                    dispatch="bare", include_enclosing=True,
                ):
                    continue
                yield ctx.finding(
                    self, node,
                    f"`{name}(...)` in `{qual}` reaches the bind POST "
                    f"with no lease fence check reachable (no "
                    f"check_fence/owns on any path) — a deposed "
                    f"scheduler can double-bind a pod; route the bind "
                    f"through the fenced binder, or justify via pragma",
                )


PROTOCOL_RULES: list[LintRule] = [
    SwapWithoutEpochBump(),
    BindWithoutFenceCheck(),
]
