"""py310 rule family — the four tools/py310_lint.py regex checks, ported.

The seed's entire tier-1 failure set (20 tests) traced to one root cause:
``asyncio.timeout(...)`` (3.11+) on a 3.10 interpreter. These rules keep
3.11+-only APIs out of the >=3.10 codebase. They stay LINE-based on
purpose: two of the four targets (``except*`` syntax and bad imports in
lazily-imported files) must be catchable even in files that would not
parse or import cleanly, which is exactly when an AST rule goes blind.

Pragmas: the historical trailing ``# py310-ok`` works everywhere (the
framework maps it to this whole family), as does
``# graftlint: ok[py310] — reason``. Comment-only lines are skipped so
prose ABOUT these APIs stays lintable.

tools/py310_lint.py remains as a thin shim over this module so existing
invocations (standalone script, tests/test_py310_lint.py) keep passing.
"""

from __future__ import annotations

import re
from typing import Iterable

from tools.graftlint.core import FileContext, Finding, LintRule

# (rule id, pattern, message) — messages identical to the original tool
# so existing suppressions/docs stay accurate.
PY310_CHECKS: tuple[tuple[str, re.Pattern[str], str], ...] = (
    (
        "py310-asyncio-timeout",
        re.compile(r"\basyncio\s*\.\s*timeout\s*\("),
        "asyncio.timeout() is 3.11+; use "
        "k8s_llm_scheduler_tpu.testing.async_deadline()",
    ),
    (
        "py310-asyncio-timeout",
        # the from-import spelling evades the dotted pattern above
        re.compile(r"from\s+asyncio\s+import\s+[^\n]*\btimeout\b"),
        "asyncio.timeout is 3.11+; use "
        "k8s_llm_scheduler_tpu.testing.async_deadline()",
    ),
    (
        "py310-exception-group",
        re.compile(r"\b(?:Base)?ExceptionGroup\b"),
        "ExceptionGroup builtins are 3.11+; the package floor is 3.10",
    ),
    (
        "py310-except-star",
        re.compile(r"\bexcept\s*\*"),
        "except* syntax is 3.11+; the package floor is 3.10",
    ),
)


class _Py310Rule(LintRule):
    family = "py310"
    needs_ast = False

    def __init__(self, rule_id: str) -> None:
        self.id = rule_id
        self._checks = [c for c in PY310_CHECKS if c[0] == rule_id]
        self.description = self._checks[0][2]

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for lineno, line in enumerate(ctx.lines, start=1):
            if line.lstrip().startswith("#"):
                continue
            for _id, pattern, message in self._checks:
                if pattern.search(line):
                    yield ctx.finding(self, lineno, message)


PY310_RULES: list[LintRule] = [
    _Py310Rule("py310-asyncio-timeout"),
    _Py310Rule("py310-exception-group"),
    _Py310Rule("py310-except-star"),
]
