"""resilience rule family — fault-handling paths that fail silently.

Grown alongside the chaos plane (chaos/): every rule here encodes a
pattern the chaos harness exists to expose at runtime, caught statically
instead.

- **swallowed-exception**: a BROAD catch (bare ``except:``, ``except
  Exception``, ``except BaseException``) whose body is only ``pass`` /
  ``...``. On a fault-handling path this erases the very signal the
  retry/breaker/fallback stack keys on. Narrow catches (``except
  OSError: pass`` around a socket close) are deliberate cleanup and stay
  legal — the hazard is breadth x silence, not silence alone.
- **unbounded-retry**: a ``while True`` loop whose exception handler
  ``continue``s straight back into the attempt with no backoff (no
  sleep-shaped call) and no escape (``break``/``return``/``raise``) in
  the handler. Retry-forever is often CORRECT for supervision loops —
  but only with backoff between attempts; without it a dead dependency
  turns the loop into a busy-spin that hammers whatever it is retrying
  (the thundering-herd shape the breaker's cooldown jitter exists to
  break up).
- **raw-clock**: a direct ``time.time()`` / ``time.sleep()`` CALL in a
  runtime module (``k8s_llm_scheduler_tpu/``). Runtime time judgments
  must ride an injectable clock (the ``clock=time.monotonic`` default-
  arg convention) so chaos and failover tests can advance virtual time
  instead of sleeping — ``fleet/lease.py`` and ``core/breaker.py`` are
  the reference shape. Referencing ``time.monotonic``/``time.sleep`` as
  a DEFAULT ARGUMENT is exactly the sanctioned pattern and is not a
  call, so it never trips. Tests, tools, and bench.py pace real wall
  time by design and are out of scope (the fixture corpus under
  tests/fixtures/graftlint stays in scope so the detectors stay
  testable).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    dotted_name,
)

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Name):
        return handler.type.id in _BROAD
    if isinstance(handler.type, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD
            for e in handler.type.elts
        )
    return False


def _is_silent(body: list[ast.stmt]) -> bool:
    """Only pass / bare `...` — nothing recorded, nothing re-raised."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant) \
                and stmt.value.value is Ellipsis:
            continue
        return False
    return True


class SwallowedException(LintRule):
    id = "swallowed-exception"
    family = "resilience"
    description = (
        "broad except (bare/Exception/BaseException) whose body is only "
        "pass — a fault-handling path that erases its own failure signal"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.all_nodes():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _is_silent(node.body):
                caught = (
                    "bare except" if node.type is None
                    else dotted_name(node.type) or "broad tuple"
                )
                # anchor on the silent statement — the line a pragma
                # naturally annotates
                yield ctx.finding(
                    self, node.body[0],
                    f"swallowed exception: {caught} handled with only "
                    f"`pass` — record it, narrow it, or justify via pragma",
                )


def _has_sleepish_call(nodes: list[ast.AST]) -> bool:
    for n in nodes:
        if isinstance(n, ast.Call):
            name = dotted_name(n.func)
            tail = name.rsplit(".", 1)[-1]
            if tail in ("sleep", "wait") or "backoff" in name.lower():
                return True
    return False


class UnboundedRetry(LintRule):
    id = "unbounded-retry"
    family = "resilience"
    description = (
        "while-True retry loop whose except handler continues with no "
        "backoff and no escape — a busy-spin against a dead dependency"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.all_nodes():
            if not isinstance(node, ast.While):
                continue
            test = node.test
            if not (isinstance(test, ast.Constant) and bool(test.value)):
                continue
            for handler in (
                n for n in ast.walk(node) if isinstance(n, ast.ExceptHandler)
            ):
                sub = list(ast.walk(handler))
                cont = next(
                    (n for n in sub if isinstance(n, ast.Continue)), None
                )
                has_escape = any(
                    isinstance(n, (ast.Break, ast.Return, ast.Raise))
                    for n in sub
                )
                if cont is not None and not has_escape \
                        and not _has_sleepish_call(sub):
                    # anchor on the `continue` — the line a pragma
                    # naturally annotates
                    yield ctx.finding(
                        self, cont,
                        "retry loop without a backoff cap: handler "
                        "continues the while-True immediately — add "
                        "backoff (sleep) or a bounded escape",
                    )


# `_time` covers the repo's local-import alias (`import time as _time`)
# — an alias must not evade the rule
_RAW_CLOCK_CALLS = ("time.time", "time.sleep", "_time.time", "_time.sleep")


def _in_scope(name: str) -> bool:
    if name.startswith("k8s_llm_scheduler_tpu/"):
        return True
    # the fixture corpus must stay lintable or the detector is untestable
    return "fixtures/graftlint" in name


class RawClock(LintRule):
    id = "raw-clock"
    family = "resilience"
    description = (
        "raw time.time()/time.sleep() call in a runtime module — take an "
        "injectable clock (clock=time.monotonic default-arg convention) "
        "so chaos/failover tests can use virtual time"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _in_scope(ctx.name):
            return
        for node in ctx.all_nodes():
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func) in _RAW_CLOCK_CALLS:
                yield ctx.finding(
                    self, node,
                    f"raw {dotted_name(node.func)}() in a runtime module: "
                    f"inject the clock/sleep instead (or justify via "
                    f"pragma)",
                )


RESILIENCE_RULES: list[LintRule] = [
    SwallowedException(),
    UnboundedRetry(),
    RawClock(),
]
