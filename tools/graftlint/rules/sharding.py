"""GSPMD sharding rule family: the tp>1 serving plane's contracts.

SCALING.md round 18's guarantee — token-identical serving across
tp=1/2/4/8 — holds only while the sharded plane keeps three disciplines
that GSPMD itself never enforces:

- **unconstrained-sharding** (moved here from the jax family when it
  went interprocedural): a jit root in a mesh-context module whose
  reachable body never constrains a sharding leaves every intermediate
  at GSPMD's default — replicated — which silently serializes the tp
  mesh. Constraint evidence is now found ANYWHERE the whole-repo graph
  can reach from the root, not just in the defining module.
- **unknown-mesh-axis**: `PartitionSpec` axis names are strings; GSPMD
  treats an axis the mesh doesn't declare as "replicate", so
  ``P("tensor")`` where the mesh says ``tp`` is not an error anywhere —
  it is a silent 8x memory/compute regression. Literal specs are
  validated against the declared table (``MESH_AXES`` in
  engine/sharded/geometry.py; a standalone file may declare its own).
- **sharded-host-pull**: `jax.device_get` (and placement-free
  `jax.device_put`, which implicitly reshards onto the default device)
  on the sharded serving path gathers a distributed value through one
  host — the all-gather the sharded plane exists to avoid. The ONE
  per-decision result pull is legitimate and pragma-justified.
- **donated-buffer-escape**: `donate_argnums` on a jit site in a
  mesh-context module that declares no shardings for the donated
  positions (no ``in_shardings``, no bound sharding bundle) — XLA can
  only alias donated buffers whose input and output shardings match, so
  a donation that escapes the `EngineShardings` bundle degrades to a
  silent copy (donation wasted) or an implicit reshard of a dead buffer.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.graftlint.core import (
    FileContext,
    Finding,
    LintRule,
    body_walk,
    dotted_name,
)
from tools.graftlint.rules.jaxpurity import (
    _is_jit_call,
    _jit_wrap_info,
    _loop_scope,
    _wrapped_bare_name_of,
)

# Names whose presence marks a module as MESH-CONTEXT: it builds or
# consumes a device mesh, so its jitted programs run under GSPMD and
# every per-op default is "replicate" unless somebody says otherwise.
_MESH_MARKERS = frozenset({
    "Mesh", "NamedSharding", "PartitionSpec", "make_mesh",
    "mesh_from_config", "shard_map", "shard_params", "build_plane",
    "kv_cache_spec", "serving_param_specs", "EngineShardings",
})
# Calls that constitute sharding evidence inside a traced function.
_CONSTRAINT_CALLS = frozenset({
    "with_sharding_constraint", "constrain", "device_put",
})


def _mesh_context(ctx: FileContext) -> bool:
    for node in ctx.all_nodes():
        if isinstance(node, ast.ImportFrom):
            if any(a.name in _MESH_MARKERS for a in node.names):
                return True
        elif isinstance(node, (ast.Name, ast.Attribute)):
            name = dotted_name(node)
            if name and name.rsplit(".", 1)[-1] in _MESH_MARKERS:
                return True
    return False


class UnconstrainedSharding(LintRule):
    id = "unconstrained-sharding"
    family = "sharding"
    description = (
        "a jit root in a mesh-context module whose inputs never see a "
        "sharding constraint — GSPMD defaults every unconstrained "
        "intermediate to replicated, silently serializing the tp mesh"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        # Runtime modules only (+ the fixture corpus): tests/tools jit
        # abstract shapes whose shardings ride in ShapeDtypeStructs the
        # AST cannot see.
        if not _loop_scope(ctx.name):
            return
        if not _mesh_context(ctx):
            return
        repo = ctx.repo
        jit_roots = repo.jit_roots()
        # Local jit call sites: in_/out_shardings kwargs, or a
        # functools.partial binding a sharding bundle by keyword
        # (`jax.jit(functools.partial(_impl, shardings=...))` — the
        # engine's idiom) are constraint evidence for the wrapped name.
        constrained: set[str] = set()
        sites: dict[str, ast.Call] = {}
        for node in ctx.all_nodes():
            if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
                bare = _wrapped_bare_name_of(node.args[0])
                if not bare:
                    continue
                if self._site_constrained(node):
                    constrained.add(bare)
                else:
                    sites.setdefault(bare, node)
        for qual, func, _cls in ctx.graph_funcs():
            g = ctx.gqual(qual)
            if g not in jit_roots:
                continue
            bare = qual.rsplit(".", 1)[-1]
            if bare in constrained:
                continue
            # the interprocedural upgrade: constraint evidence counts
            # wherever the repo graph can reach from this root — the
            # engine's jitted impls call constrain() helpers that live
            # in parallel/sharding.py, two modules away
            if repo.reaches(g, self._entry_constrains, dispatch="strict"):
                continue
            site = sites.get(bare, func)
            yield ctx.finding(
                self, site,
                f"jit root `{qual}` in a mesh-context module never "
                f"constrains a sharding (no with_sharding_constraint/"
                f"constrain/device_put reachable, no in_/out_shardings, "
                f"no bound sharding bundle) — GSPMD will replicate every "
                f"input across the mesh; thread an EngineShardings bundle "
                f"or justify via pragma",
            )

    @staticmethod
    def _entry_constrains(entry) -> bool:
        for call in entry.calls:
            name = call["n"]
            if name.rsplit(".", 1)[-1] in _CONSTRAINT_CALLS:
                return True
            # method call on a sharding bundle: shardings.kv5(x)
            if "shard" in name.split(".", 1)[0]:
                return True
        return False

    @staticmethod
    def _site_constrained(call: ast.Call) -> bool:
        if any(
            kw.arg in ("in_shardings", "out_shardings", "in_specs", "out_specs")
            for kw in call.keywords
        ):
            return True
        wrapped = call.args[0]
        if isinstance(wrapped, ast.Call) and dotted_name(wrapped.func) in (
            "partial", "functools.partial",
        ):
            return any(
                kw.arg and "shard" in kw.arg for kw in wrapped.keywords
            )
        return False


class UnknownMeshAxis(LintRule):
    id = "unknown-mesh-axis"
    family = "sharding"
    description = (
        "a PartitionSpec string literal naming an axis the declared "
        "mesh-axes table (engine/sharded/geometry.MESH_AXES) does not "
        "contain — GSPMD silently replicates along a typo'd axis"
    )

    _TABLE_MODULE = "engine/sharded/geometry.py"
    _TABLE_NAME = "MESH_AXES"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        repo = ctx.repo
        axes = repo.str_tuple(self._TABLE_MODULE, self._TABLE_NAME)
        if axes is None:
            # standalone files (fixtures, snippets) may carry their own
            # declaration; without ANY table there is nothing to check
            idx = repo.modules.get(ctx.name)
            axes = idx.str_tuples.get(self._TABLE_NAME) if idx else None
        if not axes:
            return
        known = set(axes)
        # local aliases of PartitionSpec (`from jax.sharding import
        # PartitionSpec as P` is the repo idiom)
        aliases = {"PartitionSpec"}
        for node in ctx.all_nodes():
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "PartitionSpec":
                        aliases.add(a.asname or a.name)
        for node in ctx.all_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            if name not in aliases and name.rsplit(".", 1)[-1] != "PartitionSpec":
                continue
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                            and sub.value not in known:
                        yield ctx.finding(
                            self, sub,
                            f"PartitionSpec names axis `{sub.value}`, which "
                            f"the declared mesh-axes table "
                            f"({self._TABLE_NAME} = {tuple(sorted(known))}) "
                            f"does not contain — GSPMD treats an undeclared "
                            f"axis as 'replicate', so this spec silently "
                            f"stops sharding; fix the axis name or add it "
                            f"to the table",
                        )


def _sharded_seed_module(name: str) -> bool:
    """Modules whose functions seed the tp>1 serving path: the sharded
    plane package itself, plus sharded fixtures (which stand in for a
    plane module in the self-contained corpus)."""
    if "engine/sharded/" in name:
        return True
    return "fixtures/graftlint" in name and "sharded" in name.rsplit("/", 1)[-1]


class ShardedHostPull(LintRule):
    id = "sharded-host-pull"
    family = "sharding"
    description = (
        "jax.device_get (or placement-free jax.device_put, an implicit "
        "reshard) reachable from the tp>1 serving path — gathers a "
        "distributed value through one host"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        repo = ctx.repo
        seeds = [
            g for g in repo.funcs
            if _sharded_seed_module(repo.func_module[g])
        ]
        if not seeds:
            return
        reach = repo.reachable(frozenset(seeds), dispatch="strict")
        for qual, func, _cls in ctx.graph_funcs():
            if ctx.gqual(qual) not in reach:
                continue
            for node in body_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node)
                if msg:
                    yield ctx.finding(
                        self, node,
                        f"{msg} inside `{qual}`, reachable from the sharded "
                        f"serving plane — on a tp>1 mesh this gathers the "
                        f"full distributed value through one host, the "
                        f"exact all-gather the sharded plane exists to "
                        f"avoid; keep results device-resident (or justify "
                        f"the single per-decision pull via pragma)",
                    )

    @staticmethod
    def _classify(call: ast.Call) -> str | None:
        name = dotted_name(call.func)
        if name in ("jax.device_get", "device_get"):
            return f"host pull `{name}(...)`"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "addressable_data":
            return "host pull `.addressable_data()`"
        if name == "jax.device_put" and len(call.args) < 2 and not any(
            kw.arg in ("device", "sharding", "donate") for kw in call.keywords
        ):
            return "placement-free `jax.device_put(...)` (implicit reshard)"
        return None


class DonatedBufferEscape(LintRule):
    id = "donated-buffer-escape"
    family = "sharding"
    description = (
        "donate_argnums on a jit site in a mesh-context module with no "
        "declared shardings — XLA only aliases donations whose in/out "
        "shardings match, so the donation escapes the EngineShardings "
        "bundle and degrades to a silent copy"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _loop_scope(ctx.name):
            return
        if not _mesh_context(ctx):
            return
        for node in ctx.all_nodes():
            if not isinstance(node, ast.Call):
                continue
            info = _jit_wrap_info(node)
            if info is None or not info[3]:  # no donate_argnums
                continue
            if self._site_declares_shardings(node):
                continue
            yield ctx.finding(
                self, node,
                f"jit site donates positions {info[3]} but declares no "
                f"shardings (no in_shardings, no bound sharding bundle) "
                f"in a mesh-context module — XLA cannot alias a donated "
                f"buffer across mismatched shardings, so the donation "
                f"silently degrades to a copy (and the caller still "
                f"treats the input as dead); thread the EngineShardings "
                f"bundle or justify via pragma",
            )

    @staticmethod
    def _site_declares_shardings(call: ast.Call) -> bool:
        if any(
            kw.arg in ("in_shardings", "out_shardings")
            for kw in call.keywords
        ):
            return True
        wrapped = call.args[0]
        if isinstance(wrapped, ast.Call) and dotted_name(wrapped.func) in (
            "partial", "functools.partial",
        ):
            return any(kw.arg and "shard" in kw.arg for kw in wrapped.keywords)
        return False


SHARDING_RULES: list[LintRule] = [
    UnconstrainedSharding(),
    UnknownMeshAxis(),
    ShardedHostPull(),
    DonatedBufferEscape(),
]
