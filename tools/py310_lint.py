"""Repo lint: keep Python-3.11+-only APIs out of a >=3.10 codebase.

The seed's entire tier-1 failure set (20 tests) traced to one root cause:
tests calling ``asyncio.timeout(...)``, which does not exist before 3.11,
on a 3.10 interpreter. This check makes that regression class impossible to
land silently again: it greps every tracked source/test file for

- direct ``asyncio.timeout(`` calls  -> use
  k8s_llm_scheduler_tpu.testing.async_deadline() instead;
- ``ExceptionGroup`` / ``BaseExceptionGroup`` bare use (the builtins are
  3.11+; 3.10 needs the exceptiongroup backport, which this repo does not
  vendor);
- ``except*`` clauses (3.11+ syntax — a SyntaxError at import time on
  3.10, but the lint catches it in files that are only imported lazily).

Suppress a genuinely-safe line (e.g. a feature-detect on the 3.11 branch)
with a trailing ``# py310-ok`` pragma. Comment-only lines are skipped so
prose ABOUT these APIs stays lintable.

Runs standalone (``python tools/py310_lint.py`` — exit 1 on violations)
and under pytest (tests/test_py310_lint.py).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent

# Directories that hold first-party Python (skip caches, assets, deploy).
SCAN_DIRS = ("k8s_llm_scheduler_tpu", "tests", "tools")
SCAN_FILES = ("bench.py", "__graft_entry__.py")

PRAGMA = "# py310-ok"

CHECKS: tuple[tuple[re.Pattern[str], str], ...] = (
    (
        re.compile(r"\basyncio\s*\.\s*timeout\s*\("),
        "asyncio.timeout() is 3.11+; use "
        "k8s_llm_scheduler_tpu.testing.async_deadline()",
    ),
    (
        # the from-import spelling evades the dotted pattern above
        re.compile(r"from\s+asyncio\s+import\s+[^\n]*\btimeout\b"),
        "asyncio.timeout is 3.11+; use "
        "k8s_llm_scheduler_tpu.testing.async_deadline()",
    ),
    (
        re.compile(r"\b(?:Base)?ExceptionGroup\b"),
        "ExceptionGroup builtins are 3.11+; the package floor is 3.10",
    ),
    (
        re.compile(r"\bexcept\s*\*"),
        "except* syntax is 3.11+; the package floor is 3.10",
    ),
)


def iter_py_files() -> list[Path]:
    out: list[Path] = []
    for d in SCAN_DIRS:
        root = REPO_ROOT / d
        if root.is_dir():
            out.extend(sorted(root.rglob("*.py")))
    for f in SCAN_FILES:
        p = REPO_ROOT / f
        if p.is_file():
            out.append(p)
    self_path = Path(__file__).resolve()
    return [p for p in out if p.resolve() != self_path]


def scan_text(text: str, name: str) -> list[str]:
    """Violations in one file's text as 'name:lineno: message' strings."""
    violations: list[str] = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.lstrip()
        if stripped.startswith("#") or PRAGMA in line:
            continue
        for pattern, message in CHECKS:
            if pattern.search(line):
                violations.append(f"{name}:{lineno}: {message}")
    return violations


def run() -> list[str]:
    violations: list[str] = []
    for path in iter_py_files():
        rel = path.relative_to(REPO_ROOT)
        violations.extend(scan_text(path.read_text(), str(rel)))
    return violations


def main() -> int:
    violations = run()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"py310-lint: {len(violations)} violation(s) — 3.11+-only APIs "
            f"in a >=3.10 codebase",
            file=sys.stderr,
        )
        return 1
    print(f"py310-lint: OK ({len(iter_py_files())} files scanned)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
