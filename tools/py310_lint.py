"""Repo lint: keep Python-3.11+-only APIs out of a >=3.10 codebase.

The seed's entire tier-1 failure set (20 tests) traced to one root cause:
tests calling ``asyncio.timeout(...)``, which does not exist before 3.11,
on a 3.10 interpreter. This check makes that regression class impossible
to land silently again.

NOW A THIN SHIM: the four checks live in tools/graftlint (the AST
static-analysis framework) as the ``py310`` rule family — run
``python -m tools.graftlint --rules py310`` for the same scan with the
framework's output options, or ``python -m tools.graftlint`` for the full
rule set (concurrency + JAX purity + py310). This module keeps the
historical entry points — ``python tools/py310_lint.py``, and the
``run()`` / ``scan_text()`` / ``iter_py_files()`` API that
tests/test_py310_lint.py pins — with identical messages, exit codes, and
``# py310-ok`` pragma semantics.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftlint.core import iter_repo_files, lint_text  # noqa: E402
from tools.graftlint.rules.py310 import (  # noqa: E402,F401  (CHECKS: compat)
    PY310_CHECKS as CHECKS,
    PY310_RULES,
)

PRAGMA = "# py310-ok"


def iter_py_files() -> list[Path]:
    """The first-party file set (shared with graftlint; excludes the lint
    machinery's own pattern tables and fixture corpus)."""
    return iter_repo_files(REPO_ROOT)


def scan_text(text: str, name: str) -> list[str]:
    """Violations in one file's text as 'name:lineno: message' strings.

    The framework injects a `parse-error` finding for unparseable input;
    the historical scanner was regex-only and reported exactly the py310
    messages (the except* check EXISTS for files that don't parse), so
    that companion finding is filtered here to keep the pinned contract."""
    report = lint_text(text, name, PY310_RULES)
    return [
        f"{f.path}:{f.line}: {f.message}"
        for f in report.findings
        if f.rule != "parse-error"
    ]


def run() -> list[str]:
    violations: list[str] = []
    for path in iter_py_files():
        rel = path.relative_to(REPO_ROOT)
        violations.extend(scan_text(path.read_text(), str(rel)))
    return violations


def main() -> int:
    violations = run()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"py310-lint: {len(violations)} violation(s) — 3.11+-only APIs "
            f"in a >=3.10 codebase",
            file=sys.stderr,
        )
        return 1
    print(f"py310-lint: OK ({len(iter_py_files())} files scanned)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
